"""Streaming controller daemon (daemon/): tailer semantics, epoch-pinned
serving, batch-loop decision identity, SIGTERM/checkpoint/resume
bit-equality, and the decayed-fold/mini-batch property contracts."""

import json
import os
import threading
import time

import numpy as np
import pytest

from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.daemon import (
    DaemonConfig,
    EpochPublisher,
    PlacementEpoch,
    StreamDaemon,
    tail_binary_log,
)
from cdrs_tpu.io.events import EventLog, Manifest
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=150, seed=31))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=600.0, seed=32))
    return manifest, events


def _cfg(**kw):
    base = dict(window_seconds=120.0, backend="numpy",
                kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config())
    base.update(kw)
    return ControllerConfig(**base)


def _strip(records):
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


def _slice_log(events, lo, hi):
    return EventLog(ts=events.ts[lo:hi], path_id=events.path_id[lo:hi],
                    op=events.op[lo:hi], client_id=events.client_id[lo:hi],
                    clients=events.clients)


# -- tailer -----------------------------------------------------------------

def test_tailer_static_file_matches_batch_reader(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "t.cdrsb")
    events.write_binary(p, manifest, block_rows=999)
    got = list(tail_binary_log(p, manifest))
    back = EventLog.concat([b.events for b in got])
    np.testing.assert_array_equal(back.ts, events.ts)
    np.testing.assert_array_equal(back.path_id, events.path_id)
    np.testing.assert_array_equal(back.client_id, events.client_id)
    # Offsets are strictly increasing block boundaries, each a valid
    # resume point reproducing the exact remainder.
    offs = [b.offset for b in got]
    assert offs == sorted(set(offs))
    mid = got[len(got) // 2]
    resumed = list(tail_binary_log(p, manifest, start_offset=mid.offset))
    tail = EventLog.concat([b.events for b in resumed])
    done = sum(len(b.events) for b in got[:len(got) // 2])
    np.testing.assert_array_equal(tail.ts, events.ts[done:])


def test_tailer_missing_and_torn_errors(tmp_path, workload):
    manifest, events = workload
    missing = str(tmp_path / "nope.cdrsb")
    with pytest.raises(FileNotFoundError, match="missing event log"):
        list(tail_binary_log(missing, manifest))
    # Non-follow over a file ending mid-block: the reader's canonical
    # one-line error (a static torn tail IS corruption).
    p = str(tmp_path / "torn.cdrsb")
    events.write_binary(p, manifest, block_rows=997)
    with open(p, "rb") as f:
        blob = f.read()
    with open(p, "wb") as f:
        f.write(blob[:-37])
    with pytest.raises(ValueError, match="truncated/corrupt block"):
        list(tail_binary_log(p, manifest))
    # A file ending inside the header is the header-shape error.
    h = str(tmp_path / "head.cdrsb")
    with open(h, "wb") as f:
        f.write(blob[:40])
    with pytest.raises(ValueError, match="truncated/corrupt header"):
        list(tail_binary_log(h, manifest))


def test_tailer_follow_waits_out_live_appends(tmp_path, workload):
    """A writer appending whole blocks mid-follow: the tailer surfaces
    each block once, never a torn prefix, and honors the stop predicate."""
    manifest, events = workload
    p = str(tmp_path / "live.cdrsb")
    n = len(events)
    cuts = [0, n // 3, 2 * n // 3, n]
    _slice_log(events, cuts[0], cuts[1]).write_binary(p, manifest)

    def writer():
        for lo, hi in zip(cuts[1:-1], cuts[2:]):
            time.sleep(0.15)
            _slice_log(events, lo, hi).write_binary(p, manifest,
                                                    append=True)

    seen = 0
    done = threading.Event()
    t = threading.Thread(target=writer)
    t.start()
    got = []
    for b in tail_binary_log(p, manifest, follow=True, poll=0.05,
                             stop=done.is_set):
        got.append(b.events)
        seen += len(b.events)
        if seen >= n:
            done.set()
    t.join()
    back = EventLog.concat(got)
    np.testing.assert_array_equal(back.ts, events.ts)


# -- epochs -----------------------------------------------------------------

def _epoch(i, n=16, resolver=None):
    return PlacementEpoch(epoch_id=i, window=i - 1, plan_hash=f"h{i}",
                          rf=np.full(n, i, dtype=np.int32),
                          category_idx=np.full(n, i % 4, dtype=np.int32),
                          n_nodes=3, resolver=resolver)


def test_publisher_monotonic_and_frozen():
    pub = EpochPublisher()
    pub.publish(_epoch(1))
    pub.publish(_epoch(2))
    with pytest.raises(ValueError, match="epoch ids must grow"):
        pub.publish(_epoch(2))
    ep = pub.pin()
    assert ep.epoch_id == 2 and pub.published_total == 2
    with pytest.raises(ValueError):
        ep.rf[0] = 99  # pinned plans are immutable snapshots


def test_epoch_pinning_no_torn_reads_under_publication():
    """Property: a reader pins ONCE per request batch; every value it
    reads through that pin belongs to one epoch — never a mix — while a
    publisher swaps epochs concurrently.  Each epoch is self-consistent
    by construction (rf == epoch_id everywhere), so any mixed read
    would show two different values inside one batch."""
    pub = EpochPublisher()
    pub.publish(_epoch(1))
    stop = threading.Event()
    torn = []

    def reader():
        rng = np.random.default_rng(0)
        while not stop.is_set():
            ep = pub.pin()  # pin once ...
            vals = set()
            for _ in range(8):  # ... hold for the whole request batch
                idx = rng.integers(0, len(ep.rf), size=4)
                vals.update(int(v) for v in ep.rf[idx])
                vals.add(int(ep.epoch_id))
                vals.add(int(ep.category_idx[int(idx[0])]) * 0
                         + int(ep.rf[int(idx[1])]))
            if len(vals) != 1:
                torn.append(vals)
                return

    readers = [threading.Thread(target=reader) for _ in range(4)]
    for r in readers:
        r.start()
    for i in range(2, 250):
        pub.publish(_epoch(i))
    stop.set()
    for r in readers:
        r.join()
    assert not torn, f"mixed-epoch read observed: {torn[:3]}"
    assert pub.pin().epoch_id == 249


# -- daemon vs batch controller ---------------------------------------------

def test_daemon_decisions_identical_to_batch_run(tmp_path, workload):
    manifest, events = workload
    batch = ReplicationController(manifest, _cfg()).run(events)
    # In-memory feed.
    mem = StreamDaemon(ReplicationController(manifest, _cfg()))
    dig = mem.run(events)
    assert _strip(mem.records) == _strip(batch.records)
    assert dig["epochs_published"] == len(batch.records) >= 2
    # Binary log through the tailer.
    p = str(tmp_path / "ev.cdrsb")
    events.write_binary(p, manifest, block_rows=1013)
    d2 = StreamDaemon(ReplicationController(manifest, _cfg()))
    d2.run(p)
    assert _strip(d2.records) == _strip(batch.records)
    # The served epoch is the final applied plan.
    ep = mem.publisher.pin()
    assert ep.plan_hash == mem.records[-1]["plan_hash"]
    rv = ep.read_view(np.array([0, 5, 5, 1], dtype=np.int32))
    assert rv.replica_map.shape[1] >= 1


def test_daemon_epoch_rf_tracks_applied_plan(workload):
    manifest, events = workload
    d = StreamDaemon(ReplicationController(manifest, _cfg()))
    d.run(events)
    ep = d.publisher.pin()
    np.testing.assert_array_equal(ep.rf, d.controller.current_rf)
    np.testing.assert_array_equal(ep.category_idx, d.controller.current_cat)


def test_daemon_rejects_csv_source(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "a.log")
    events.write_csv(p, manifest)
    d = StreamDaemon(ReplicationController(manifest, _cfg()))
    with pytest.raises(ValueError, match="binary event log"):
        d.run(p)


# -- checkpoint / SIGTERM / resume ------------------------------------------

def test_daemon_resume_bit_identical_mid_epoch(tmp_path, workload):
    """Stop after 2 windows (mid-epoch-stream), resume: the two runs'
    records concatenate to exactly the uninterrupted run's, epoch ids
    stay continuous, and the resume reads only the unprocessed tail."""
    manifest, events = workload
    full = StreamDaemon(ReplicationController(manifest, _cfg()))
    full.run(events)
    p = str(tmp_path / "ev.cdrsb")
    ck = str(tmp_path / "d.ckpt")
    events.write_binary(p, manifest, block_rows=2048)

    d1 = StreamDaemon(ReplicationController(manifest, _cfg()),
                      DaemonConfig(max_windows=2))
    dig1 = d1.run(p, checkpoint_path=ck)
    assert dig1["stop_reason"] == "max_windows"
    d2 = StreamDaemon(ReplicationController(manifest, _cfg()))
    dig2 = d2.run(p, checkpoint_path=ck)
    assert _strip(d1.records) + _strip(d2.records) == _strip(full.records)
    assert dig2["epochs_published"] == len(full.records)
    assert d2.events_ingested < len(events)  # O(new data), not O(history)
    np.testing.assert_array_equal(d2.controller.current_rf,
                                  full.controller.current_rf)
    np.testing.assert_array_equal(d2.controller.current_cat,
                                  full.controller.current_cat)


def test_daemon_stop_mid_backlog_resumes_bit_identical(tmp_path, workload):
    """A stop landing between windows (follow mode, unprocessed events
    buffered past the cursor) must not fold the in-flight partial
    window: resume re-reads it and the joined records stay exact."""
    manifest, events = workload
    full = StreamDaemon(ReplicationController(manifest, _cfg()))
    full.run(events)
    p = str(tmp_path / "ev.cdrsb")
    ck = str(tmp_path / "d.ckpt")
    events.write_binary(p, manifest, block_rows=512)

    d1 = StreamDaemon(ReplicationController(manifest, _cfg()),
                      DaemonConfig(follow=True, poll=0.05))
    timer = threading.Timer(0.6, d1.request_stop, args=("SIGTERM",))
    timer.start()
    dig1 = d1.run(p, checkpoint_path=ck)
    timer.cancel()
    assert dig1["stop_reason"] == "SIGTERM"
    d2 = StreamDaemon(ReplicationController(manifest, _cfg()))
    d2.run(p, checkpoint_path=ck)
    assert _strip(d1.records) + _strip(d2.records) == _strip(full.records)


def test_daemon_checkpoint_carries_cursor_meta(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "ev.cdrsb")
    ck = str(tmp_path / "d.ckpt")
    events.write_binary(p, manifest)
    d = StreamDaemon(ReplicationController(manifest, _cfg()),
                     DaemonConfig(max_windows=1))
    dig = d.run(p, checkpoint_path=ck)
    ctl = ReplicationController(manifest, _cfg())
    ctl.load_checkpoint(ck)
    meta = ctl.last_checkpoint_meta["daemon"]
    assert meta["offset"] == dig["cursor"]["offset"]
    assert meta["skip"] == dig["cursor"]["skip"]
    assert meta["epochs_published"] == dig["epochs_published"] == 1


# -- satellite properties ---------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_decayed_fold_decay_one_bit_identical_to_batch(seed):
    """The decayed live-statistics path at decay=1.0 is the batch fold,
    bit for bit: same feature snapshots, same records, same plans.
    (Window edges land on integer seconds, so no (file, second)
    concurrency bucket ever straddles a window boundary.)"""
    manifest = generate_population(GeneratorConfig(n_files=120,
                                                   seed=100 + seed))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=480.0,
                                             seed=200 + seed))
    a = ReplicationController(manifest, _cfg())
    b = ReplicationController(manifest, _cfg())
    # Force the decayed-accumulator path on b at g=1.0 (cfg.decay=1.0
    # normally short-circuits to the cumulative fold).
    b._dec = {k: np.zeros(len(manifest)) for k in
              ("access_freq", "writes", "local_acc", "conc_max")}
    b._dec_obs_end = None
    ra = a.run(events)
    rb = b.run(events)
    assert _strip(ra.records) == _strip(rb.records)
    np.testing.assert_array_equal(
        a._feature_snapshot(), b._feature_snapshot())
    np.testing.assert_array_equal(a.current_rf, b.current_rf)
    np.testing.assert_array_equal(a.current_cat, b.current_cat)


def test_minibatch_warm_start_inertia_within_band_of_full_lloyd():
    """Warm-started mini-batch Lloyd (what daemon --recluster minibatch
    advances per window) converges to an inertia within a pinned band of
    the full-refit Lloyd optimum on the same data."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from cdrs_tpu.ops.kmeans_np import kmeans
    from cdrs_tpu.ops.kmeans_stream import MiniBatchKMeans

    rng = np.random.default_rng(7)
    centers = rng.normal(size=(6, 5)) * 8.0
    X = np.concatenate([rng.normal(loc=c, scale=0.4, size=(300, 5))
                        for c in centers]).astype(np.float32)

    def inertia(C):
        d = X[:, None, :] - C[None, :, :]
        return float(np.mean(np.min((d * d).sum(-1), axis=1)))

    C_full, _ = kmeans(X.astype(np.float64), 6, random_state=0)
    full = inertia(C_full.astype(np.float32))

    mb = MiniBatchKMeans(k=6, seed=0)
    perm = np.random.default_rng(8).permutation(len(X))
    for _ in range(3):  # a few warm passes, daemon-style
        for lo in range(0, len(X), 256):
            mb.partial_fit(X[perm[lo:lo + 256]])
    warm = inertia(mb.centroids)
    # Pinned band: warm mini-batch within 1.5x of the full refit (and
    # both must actually separate the blobs, not merely not-crash).
    assert warm <= full * 1.5 + 1e-6, (warm, full)
    assert warm < float(np.var(X, axis=0).sum())


# -- live feed with drift + alert surface -----------------------------------

def test_daemon_follow_live_appends_with_alert_surface(tmp_path):
    """End-to-end live run: a writer appends the log while the daemon
    follows; >= 2 epochs publish, no events are lost, and the digest is
    the same as a batch daemon over the final log."""
    manifest = generate_population(GeneratorConfig(n_files=100, seed=41))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=480.0,
                                             seed=42))
    p = str(tmp_path / "live.cdrsb")
    n = len(events)
    cuts = [0, n // 4, n // 2, 3 * n // 4, n]

    def _part(i):
        return EventLog(ts=events.ts[cuts[i]:cuts[i + 1]],
                        path_id=events.path_id[cuts[i]:cuts[i + 1]],
                        op=events.op[cuts[i]:cuts[i + 1]],
                        client_id=events.client_id[cuts[i]:cuts[i + 1]],
                        clients=events.clients)

    _part(0).write_binary(p, manifest)
    d = StreamDaemon(ReplicationController(manifest, _cfg()),
                     DaemonConfig(follow=True, poll=0.05))

    def writer():
        for i in range(1, 4):
            time.sleep(0.2)
            _part(i).write_binary(p, manifest, append=True)
        # Writer done: let the daemon drain, then stop it.
        time.sleep(0.5)
        d.request_stop("writer_done")

    t = threading.Thread(target=writer)
    t.start()
    dig = d.run(p)
    t.join()
    ref = StreamDaemon(ReplicationController(manifest, _cfg()))
    ref.run(events)
    # The stop lands between windows; everything processed must match
    # the batch prefix exactly, with >= 2 epochs live-published.
    k = len(d.records)
    assert k >= 2 and dig["epochs_published"] == k
    assert _strip(d.records) == _strip(ref.records)[:k]
