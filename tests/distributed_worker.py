"""Worker process for the two-process jax.distributed smoke test.

Launched by tests/test_distributed_smoke.py as
``python tests/distributed_worker.py PORT PROCESS_ID OUTFILE``.  Each of the
two processes brings up 2 virtual CPU devices, rendezvouses through the
localhost coordinator, builds the 4-device global mesh, and runs the sharded
KMeans end to end — the DCN-tier execution path (VERDICT r4 #8: the one
comms path that had never actually executed).  The resulting centroids are
written to OUTFILE for the parent to compare across processes and against a
single-process run of the same logical mesh.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    port, process_id, outfile = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=2 "
        + os.environ.get("CDRS_EXTRA_XLA_FLAGS", ""))
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    import numpy as np

    from cdrs_tpu.parallel.distributed import (global_mesh, init_distributed,
                                               mesh_axis_sizes)

    active = init_distributed(coordinator_address=f"localhost:{port}",
                              num_processes=2, process_id=process_id)
    import jax

    assert active, "init_distributed must report a multi-process runtime"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()
    assert len(jax.local_devices()) == 2

    mesh = global_mesh()
    shape = mesh_axis_sizes(mesh)
    assert shape == {"data": 4, "model": 1}, shape

    # Deterministic workload, identical in both processes; each contributes
    # its local shards of the global array.
    rng = np.random.default_rng(7)
    X_np = rng.normal(size=(4096, 8)).astype(np.float32)
    X_np[:2048] += 4.0  # two planted blobs

    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("data", None))
    X = jax.make_array_from_callback(X_np.shape, sharding,
                                     lambda idx: X_np[idx])

    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    centroids, labels, it, shift = kmeans_jax_full(
        X, 16, seed=3, max_iter=25, mesh_shape=shape)
    out = {
        "process_id": process_id,
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "n_iter": int(it),
        "shift": float(shift),
        "centroids": np.asarray(centroids).tolist(),
    }
    with open(outfile, "w") as f:
        json.dump(out, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
