"""Scoring-config file loading: round-trip, validation, CLI wiring."""

import dataclasses
import json

import numpy as np
import pytest

from cdrs_tpu.config import (
    ScoringConfig,
    load_scoring_config,
    scoring_config_from_dict,
)


def _as_dict(cfg: ScoringConfig) -> dict:
    d = dataclasses.asdict(cfg)
    d["features"] = list(cfg.features)
    d["categories"] = list(cfg.categories)
    return d


def test_roundtrip_defaults(tmp_path):
    cfg = ScoringConfig()
    p = tmp_path / "s.json"
    p.write_text(json.dumps(_as_dict(cfg)))
    loaded = load_scoring_config(str(p))
    assert loaded == cfg


def test_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown scoring config keys"):
        scoring_config_from_dict({"wieghts": {}})


def test_missing_feature_weight_rejected():
    d = _as_dict(ScoringConfig())
    del d["weights"]["Hot"]["age_norm"]
    with pytest.raises(ValueError, match="missing features"):
        scoring_config_from_dict(d)


def test_missing_category_rejected():
    d = _as_dict(ScoringConfig())
    del d["replication_factors"]["Archival"]
    with pytest.raises(ValueError, match="replication_factors missing"):
        scoring_config_from_dict(d)


def test_custom_config_changes_classification(tmp_path):
    """A config that inflates Hot weights must be able to flip a decision."""
    from cdrs_tpu.ops.scoring_np import classify_medians

    base = ScoringConfig()
    d = _as_dict(base)
    for f in d["weights"]["Hot"]:
        d["weights"]["Hot"][f] = 100.0
    boosted = scoring_config_from_dict(d)

    medians = np.array([[0.6, 0.4, 0.6, 0.6, 0.6]])  # mildly hot-ish
    w1, _ = classify_medians(medians, base)
    w2, _ = classify_medians(medians, boosted)
    assert base.categories[int(w2[0])] == "Hot"


def test_cli_scoring_config(tmp_path):
    from cdrs_tpu.cli import main

    cfgp = tmp_path / "s.json"
    cfgp.write_text(json.dumps(_as_dict(ScoringConfig())))
    rc = main([
        "pipeline", "--n", "80", "--duration_seconds", "30", "--k", "4",
        "--outdir", str(tmp_path / "out"),
        "--scoring_config", str(cfgp), "--medians_from_data",
    ])
    assert rc == 0
    assert (tmp_path / "out" / "final_categories.csv").exists()
