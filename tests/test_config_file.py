"""Scoring-config file loading: round-trip, validation, CLI wiring."""

import dataclasses
import json

import numpy as np
import pytest

from cdrs_tpu.config import (
    ScoringConfig,
    load_scoring_config,
    scoring_config_from_dict,
)


def _as_dict(cfg: ScoringConfig) -> dict:
    d = dataclasses.asdict(cfg)
    d["features"] = list(cfg.features)
    d["categories"] = list(cfg.categories)
    return d


def test_roundtrip_defaults(tmp_path):
    cfg = ScoringConfig()
    p = tmp_path / "s.json"
    p.write_text(json.dumps(_as_dict(cfg)))
    loaded = load_scoring_config(str(p))
    assert loaded == cfg


def test_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown scoring config keys"):
        scoring_config_from_dict({"wieghts": {}})


def test_missing_feature_weight_rejected():
    d = _as_dict(ScoringConfig())
    del d["weights"]["Hot"]["age_norm"]
    with pytest.raises(ValueError, match="missing features"):
        scoring_config_from_dict(d)


def test_missing_category_rejected():
    d = _as_dict(ScoringConfig())
    del d["replication_factors"]["Archival"]
    with pytest.raises(ValueError, match="replication_factors missing"):
        scoring_config_from_dict(d)


def test_custom_config_changes_classification(tmp_path):
    """A config that inflates Hot weights must be able to flip a decision."""
    from cdrs_tpu.ops.scoring_np import classify_medians

    base = ScoringConfig()
    d = _as_dict(base)
    for f in d["weights"]["Hot"]:
        d["weights"]["Hot"][f] = 100.0
    boosted = scoring_config_from_dict(d)

    medians = np.array([[0.6, 0.4, 0.6, 0.6, 0.6]])  # mildly hot-ish
    w1, _ = classify_medians(medians, base)
    w2, _ = classify_medians(medians, boosted)
    assert base.categories[int(w2[0])] == "Hot"


def test_cli_evaluate_honors_scoring_config(tmp_path, capsys):
    """`cdrs evaluate --scoring_config` must apply the custom category -> rf
    table when placing replicas (VERDICT r1: it silently used defaults)."""
    from cdrs_tpu.cli import main
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=40, seed=2))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=30, seed=2))
    mpath, apath = tmp_path / "m.csv", tmp_path / "a.log"
    manifest.write_csv(str(mpath))
    events.write_csv(str(apath), manifest)

    # All files assigned to Hot (default rf=3; custom rf=6 below).
    assign = tmp_path / "assign.csv"
    with open(assign, "w") as f:
        f.write("path,cluster,category\n")
        for p in manifest.paths:
            f.write(f"{p},0,Hot\n")

    d = _as_dict(ScoringConfig())
    d["replication_factors"]["Hot"] = 6
    cfgp = tmp_path / "s.json"
    cfgp.write_text(json.dumps(d))

    base_args = ["evaluate", "--manifest", str(mpath), "--access_log",
                 str(apath), "--assignments_csv", str(assign)]
    # On the manifest's 3-node topology both rf tables cap to 3 replicas:
    # outputs must be identical (pins the capping behaviour).
    assert main(base_args) == 0
    default_capped = json.loads(capsys.readouterr().out)
    assert main(base_args + ["--scoring_config", str(cfgp)]) == 0
    custom_capped = json.loads(capsys.readouterr().out)
    assert custom_capped["policy"]["total_storage_bytes"] == \
        default_capped["policy"]["total_storage_bytes"]

    # With 8 nodes the custom rf=6 doubles the default rf=3 storage.
    nodes = "dn1,dn2,dn3,dn4,dn5,dn6,dn7,dn8"
    assert main(base_args + ["--nodes", nodes]) == 0
    default_out = json.loads(capsys.readouterr().out)
    assert main(base_args + ["--nodes", nodes, "--scoring_config", str(cfgp)]) == 0
    custom_out = json.loads(capsys.readouterr().out)
    assert custom_out["policy"]["total_storage_bytes"] == \
        2 * default_out["policy"]["total_storage_bytes"]


def test_cli_scoring_config(tmp_path):
    from cdrs_tpu.cli import main

    cfgp = tmp_path / "s.json"
    cfgp.write_text(json.dumps(_as_dict(ScoringConfig())))
    rc = main([
        "pipeline", "--n", "80", "--duration_seconds", "30", "--k", "4",
        "--outdir", str(tmp_path / "out"),
        "--scoring_config", str(cfgp), "--medians_from_data",
    ])
    assert rc == 0
    assert (tmp_path / "out" / "final_categories.csv").exists()
