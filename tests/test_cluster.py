"""Cluster simulator: placement invariants + policy-evaluation loop."""

import numpy as np
import pytest

from cdrs_tpu.cluster import (
    ClusterTopology,
    compare_policies,
    evaluate_placement,
    place_replicas,
)
from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.io.events import EventLog, Manifest
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=300, seed=21))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=300.0,
                                                       seed=22))
    return manifest, events


def test_placement_invariants(workload):
    manifest, _ = workload
    topo = ClusterTopology(nodes=tuple(manifest.nodes))
    rng = np.random.default_rng(0)
    rf = rng.integers(1, 5, size=len(manifest)).astype(np.int32)
    p = place_replicas(manifest, rf, topo, seed=1)

    # rf capped at node count, at least 1
    assert (p.rf == np.minimum(rf, len(topo))).all()
    for i in range(len(manifest)):
        reps = p.replica_map[i][p.replica_map[i] >= 0]
        assert len(reps) == p.rf[i]
        assert len(set(reps.tolist())) == len(reps)      # distinct nodes
        # replica 0 is the primary node
        assert p.replica_map[i, 0] == manifest.primary_node_id[i]

    # storage accounting: sum over nodes == sum(size * rf)
    assert p.storage_per_node.sum() == int(
        (manifest.size_bytes * p.rf).sum())

    # deterministic
    p2 = place_replicas(manifest, rf, topo, seed=1)
    assert (p.replica_map == p2.replica_map).all()


def test_placement_holds_property():
    """Property-style: ``PlacementResult.holds`` == per-event brute force
    over random mixed-rf placements, including out-of-topology (node < 0)
    clients — which must never match the -1 padding of short rows."""
    rng = np.random.default_rng(7)
    for trial in range(5):
        n = int(rng.integers(20, 80))
        manifest = generate_population(
            GeneratorConfig(n_files=n, seed=int(rng.integers(0, 1000))))
        topo = ClusterTopology(nodes=tuple(manifest.nodes))
        rf = rng.integers(1, 5, size=n).astype(np.int32)  # mixed-rf rows
        p = place_replicas(manifest, rf, topo, seed=trial)
        e = int(rng.integers(50, 200))
        pid = rng.integers(0, n, size=e).astype(np.int64)
        # Clients include -1 (outside the topology) and every real node.
        node = rng.integers(-1, len(topo), size=e).astype(np.int32)
        got = p.holds(pid, node)
        want = np.asarray([
            node[j] >= 0 and int(node[j]) in
            set(p.replica_map[pid[j]][p.replica_map[pid[j]] >= 0].tolist())
            for j in range(e)])
        np.testing.assert_array_equal(got, want)
        assert not got[node < 0].any()


def test_evaluate_tiny_hand_example():
    m = Manifest(paths=["/a", "/b"], creation_ts=np.zeros(2),
                 primary_node_id=np.array([0, 1], dtype=np.int32),
                 size_bytes=np.array([10, 20], dtype=np.int64),
                 category=["hot", "moderate"], nodes=["dn1", "dn2"])
    ev = EventLog(
        ts=np.arange(4, dtype=np.float64),
        path_id=np.array([0, 0, 1, 1], dtype=np.int32),
        op=np.array([0, 0, 0, 1], dtype=np.int8),       # 3 reads, 1 write
        client_id=np.array([0, 1, 1, 0], dtype=np.int32),
        clients=["dn1", "dn2"],
    )
    topo = ClusterTopology(nodes=("dn1", "dn2"))
    # rf = [2, 1]: /a on both nodes, /b only on dn2.
    p = place_replicas(m, np.array([2, 1]), topo, seed=0)
    metrics = evaluate_placement(m, ev, p, seed=0)
    # reads: /a@dn1 local, /a@dn2 local (replicated), /b@dn2 local => all local
    assert metrics.read_locality == 1.0
    assert metrics.n_reads == 3 and metrics.n_writes == 1
    # the write to /b hits exactly its single replica (dn2)
    assert metrics.writes_per_node.tolist() == [0, 1]
    assert metrics.total_storage == 10 * 2 + 20 * 1


def test_policy_beats_uniform1_locality(workload):
    """The clustering-driven factors must buy read locality over the
    reference's dfs.replication=1 at bounded storage vs uniform max-rf —
    the claim of the underlying paper, now actually measured.

    Uses the validated scoring tables (config.validated_scoring_config):
    the reference's placeholder tables collapse nearly every cluster to
    Moderate and buy ~0 locality on this workload (VERDICT r2 weak #1)."""
    from cdrs_tpu.models.replication import ReplicationPolicyModel
    from cdrs_tpu.features.numpy_backend import compute_features
    from cdrs_tpu.config import KMeansConfig, validated_scoring_config

    manifest, events = workload
    table = compute_features(manifest, events)
    scoring = validated_scoring_config()
    model = ReplicationPolicyModel(KMeansConfig(k=8, seed=42), scoring)
    decision = model.run(np.asarray(table.norm))
    rf = decision.replication_factor_per_file(scoring)

    out = compare_policies(manifest, events, rf,
                           topology=ClusterTopology(tuple(manifest.nodes)))
    # The margin is structural, not a tie-break accident: +0.10 absolute on
    # this (seeded, fixed-epoch => fully deterministic) workload.
    assert (out["policy"]["read_locality"]
            >= out["uniform_1"]["read_locality"] + 0.05)
    # storage between the uniform extremes (rf capped at 3 nodes)
    assert (out["uniform_1"]["total_storage_bytes"]
            <= out["policy"]["total_storage_bytes"]
            <= out["uniform_3"]["total_storage_bytes"])


def test_seeded_workload_is_process_deterministic(workload):
    """Seeded generator+simulator must not depend on wall clock (regression:
    time.time() anchoring shifted the concurrency second-buckets every run,
    making the policy test a coin flip across processes)."""
    manifest, events = workload
    m2 = generate_population(GeneratorConfig(n_files=300, seed=21))
    e2 = simulate_access(m2, SimulatorConfig(duration_seconds=300.0, seed=22))
    assert (m2.creation_ts == manifest.creation_ts).all()
    assert (e2.ts == events.ts).all()
    # events land after every file exists, inside the simulated window
    assert float(events.ts.min()) >= float(manifest.creation_ts.max())
    assert float(events.ts.max()) <= float(manifest.creation_ts.max()) + 302.0


def test_validated_config_recovers_planted_categories(workload):
    """Decision quality as a tracked number: the validated scoring tables
    must recover the generator's planted categories well above the
    reference tables' ~0.55 collapse-to-Moderate plateau."""
    from cdrs_tpu.models.replication import ReplicationPolicyModel
    from cdrs_tpu.features.numpy_backend import compute_features
    from cdrs_tpu.pipeline import recovery_accuracy
    from cdrs_tpu.config import KMeansConfig, validated_scoring_config

    manifest, events = workload
    table = compute_features(manifest, events)
    model = ReplicationPolicyModel(KMeansConfig(k=8, seed=42),
                                   validated_scoring_config())
    decision = model.run(np.asarray(table.norm))
    acc = recovery_accuracy(decision, manifest.category)
    assert acc is not None and acc >= 0.80
    # All four categories must actually be used (no Moderate collapse).
    assert set(decision.categories) == {"Hot", "Shared", "Moderate", "Archival"}


def test_pipeline_evaluate_flag(workload):
    from cdrs_tpu.config import (GeneratorConfig, KMeansConfig, PipelineConfig,
                                 ScoringConfig, SimulatorConfig)
    from cdrs_tpu.pipeline import run_pipeline

    cfg = PipelineConfig(
        generator=GeneratorConfig(n_files=150, seed=5),
        simulator=SimulatorConfig(duration_seconds=120.0, seed=6),
        kmeans=KMeansConfig(k=4, seed=42),
        scoring=ScoringConfig(compute_global_medians_from_data=True),
        evaluate=True,
    )
    result = run_pipeline(cfg)
    assert result.evaluation is not None
    assert set(result.evaluation) == {"uniform_1", "uniform_3", "policy"}
    for v in result.evaluation.values():
        assert 0.0 <= v["read_locality"] <= 1.0
        assert v["load_balance"] >= 1.0


def test_foreign_clients_never_count_local():
    """A client outside the topology (e.g. dn4 vs 3 datanodes) must not match
    the -1 padding of mixed-rf placements (regression: inflated locality)."""
    m = Manifest(paths=["/a", "/b"], creation_ts=np.zeros(2),
                 primary_node_id=np.array([0, 1], dtype=np.int32),
                 size_bytes=np.array([10, 10], dtype=np.int64),
                 category=["hot", "hot"], nodes=["dn1", "dn2", "dn3"])
    ev = EventLog(
        ts=np.arange(2, dtype=np.float64),
        path_id=np.array([0, 1], dtype=np.int32),
        op=np.zeros(2, dtype=np.int8),
        client_id=np.array([3, 3], dtype=np.int32),  # dn4: not in topology
        clients=["dn1", "dn2", "dn3", "dn4"],
    )
    topo = ClusterTopology(nodes=("dn1", "dn2", "dn3"))
    # mixed rf -> /b's row has a -1 padding slot
    p = place_replicas(m, np.array([2, 1]), topo, seed=0)
    metrics = evaluate_placement(m, ev, p, seed=0)
    assert metrics.read_locality == 0.0
    # both reads still get served by some real replica node
    assert metrics.reads_per_node.sum() == 2


def test_decision_quality_holds_at_larger_scale():
    """The validated scoring tables are not overfit to the 300-file
    workload: planted recovery and locality gain hold at 2000 files."""
    from cdrs_tpu.benchmarks.harness import _quality_one

    q = _quality_one(2000, 600.0, 121)
    assert q["planted_accuracy"] >= 0.75
    assert q["read_locality_gain"] >= 0.05


def test_decision_quality_holds_at_100k_files():
    """VERDICT r4 #10: the validated tables hold at 100K files (measured
    0.832 accuracy / +0.133 locality gain at seed 21; bounds leave seed
    margin).  ~18 s — the one deliberately-slow quality gate."""
    from cdrs_tpu.benchmarks.harness import _quality_one

    q = _quality_one(100_000, 600.0, 21)
    assert q["planted_accuracy"] >= 0.78
    assert q["read_locality_gain"] >= 0.08
