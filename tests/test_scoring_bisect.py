"""Bisection (scatter-free MXU) cluster medians — parity with exact/hist.

The bisect path answers ceil(log2(bins))+1 rank queries per (cluster,
feature) with the one-hot label matmul (ops/pallas_kernels.
label_segment_matmul) instead of the histogram path's per-element scatter —
~10x on a real chip at 10M x 128, k=1024 (docs/ARCHITECTURE.md).  CPU runs
the kernel in interpret mode on small workloads.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax.numpy as jnp

from cdrs_tpu.config import ScoringConfig
from cdrs_tpu.ops import scoring_np
from cdrs_tpu.ops.scoring_jax import _bisect_medians, classify_jax


def test_label_segment_matmul_matches_segment_sum():
    from cdrs_tpu.ops.pallas_kernels import label_segment_matmul

    rng = np.random.default_rng(0)
    n, d, k = 2048, 6, 5
    lab = rng.integers(-1, k, size=n).astype(np.int32)   # -1 = padding
    y = rng.uniform(size=(n, d)).astype(np.float32)
    got = np.asarray(label_segment_matmul(
        jnp.asarray(lab), jnp.asarray(y), k, tile_rows=512, interpret=True))
    want = np.zeros((k, d), np.float32)
    for j in range(k):
        want[j] = y[lab == j].sum(axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_bisect_medians_close_to_exact():
    """Within range/2^iters of exact; NaN for empty clusters."""
    rng = np.random.default_rng(3)
    X = rng.uniform(size=(40_000, 5)).astype(np.float64)
    labels = rng.integers(0, 7, size=40_000).astype(np.int32)  # cluster 7 empty
    med, gmed = _bisect_medians(jnp.asarray(X), jnp.asarray(labels), k=8,
                                bins=2048, with_global=True)
    got = np.asarray(med)
    want = scoring_np.compute_cluster_medians(X, labels, 8)
    assert np.isnan(got[7]).all()
    np.testing.assert_allclose(got[:7], want[:7], atol=1.0 / 2048)
    np.testing.assert_allclose(np.asarray(gmed), np.median(X, axis=0),
                               atol=1.0 / 2048)


def test_bisect_constant_column_exact():
    rng = np.random.default_rng(4)
    X = rng.uniform(size=(1000, 3))
    X[:, 1] = 0.25
    labels = rng.integers(0, 3, size=1000).astype(np.int32)
    med, gmed = _bisect_medians(jnp.asarray(X), jnp.asarray(labels), k=3,
                                bins=2048, with_global=True)
    assert (np.asarray(med)[:, 1] == 0.25).all()
    assert float(gmed[1]) == 0.25


@pytest.mark.parametrize("from_data", [False, True])
def test_bisect_classify_category_parity(from_data):
    """Categories from bisection medians match the exact path (SURVEY.md
    §7.4: parity on categories, not raw scores, at scale)."""
    rng = np.random.default_rng(7)
    k = 8
    centers = rng.uniform(size=(k, 5))
    lab = rng.integers(0, k, size=50_000)
    X = np.clip(centers[lab] + rng.normal(size=(50_000, 5)) * 0.05, 0, 1)
    labels = lab.astype(np.int32)

    exact = ScoringConfig(median_method="sort",
                          compute_global_medians_from_data=from_data)
    bis = ScoringConfig(median_method="bisect",
                        compute_global_medians_from_data=from_data)
    we, se, me = classify_jax(X, labels, k, exact)
    wb, sb, mb = classify_jax(X, labels, k, bis)
    np.testing.assert_allclose(np.asarray(mb), np.asarray(me), atol=1e-3)
    np.testing.assert_array_equal(np.asarray(wb), np.asarray(we))


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bisect_random_property(seed):
    """Randomized workloads: random (n, k, d), heavy duplicates in one
    column, possibly near-empty clusters — bisect medians within
    range/2^(iters-1) of the exact sort medians per feature."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(500, 4000))
    k = int(rng.integers(2, 9))
    d = int(rng.integers(2, 6))
    X = rng.uniform(size=(n, d))
    X[:, 0] = rng.integers(0, 4, size=n) / 3.0   # discrete: duplicate-heavy
    labels = rng.integers(0, k, size=n).astype(np.int32)
    med, gmed = _bisect_medians(jnp.asarray(X), jnp.asarray(labels), k=k,
                                bins=4096, with_global=True)
    want = scoring_np.compute_cluster_medians(X, labels, k)
    iters = 13  # max(8, ceil(log2(4096)) + 1)
    tol = (X.max(axis=0) - X.min(axis=0)) / 2 ** (iters - 1) + 1e-9
    got = np.asarray(med)
    present = np.bincount(labels, minlength=k) > 0
    assert np.isnan(got[~present]).all()
    assert (np.abs(got[present] - want[present]) <= tol[None, :]).all()
    assert (np.abs(np.asarray(gmed) - np.median(X, axis=0)) <= tol).all()


def test_bisect_even_odd_rank_average():
    """Even-count clusters average the two middle order stats (the sort and
    hist kernels' contract) — check on a tiny hand-computed case."""
    X = np.array([[0.0], [1.0], [2.0], [10.0],     # cluster 0: median 1.5
                  [5.0], [6.0], [7.0]])            # cluster 1: median 6.0
    labels = np.array([0, 0, 0, 0, 1, 1, 1], np.int32)
    med, _ = _bisect_medians(jnp.asarray(X), jnp.asarray(labels), k=2,
                             bins=1 << 16, with_global=False)
    np.testing.assert_allclose(np.asarray(med)[:, 0], [1.5, 6.0], atol=2e-3)


@pytest.mark.parametrize("mesh_shape", [{"data": 2}, {"data": 4, "model": 2}])
def test_sharded_bisect_matches_single_device(mesh_shape):
    """Explicit bisect on a data-sharded mesh: per-iteration psum of the
    count block must reproduce the single-device medians exactly (the
    bisection decisions are integer-count comparisons — identical on every
    shard) — including an uneven row count (sentinel-label padding)."""
    rng = np.random.default_rng(1)
    X = rng.uniform(size=(1077, 5))    # does not divide the mesh
    labels = rng.integers(0, 4, size=1077).astype(np.int32)
    cfg = ScoringConfig(median_method="bisect",
                        compute_global_medians_from_data=True)
    w1, s1, m1 = classify_jax(X, labels, 4, cfg)
    w2, s2, m2 = classify_jax(X, labels, 4, cfg, mesh_shape=mesh_shape)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m1), atol=0)
    np.testing.assert_array_equal(np.asarray(w2), np.asarray(w1))


def test_sort_still_rejected_on_sharded_mesh():
    rng = np.random.default_rng(1)
    X = rng.uniform(size=(1024, 3))
    labels = rng.integers(0, 4, size=1024).astype(np.int32)
    with pytest.raises(ValueError, match="single-device"):
        classify_jax(X, labels, 4, ScoringConfig(median_method="sort"),
                     mesh_shape={"data": 2})


def test_numpy_backend_maps_bisect_to_hist():
    """A 'bisect' config runs on the numpy backend via its accuracy twin
    (hist) instead of crashing mid-run (code-review regression)."""
    rng = np.random.default_rng(2)
    X = rng.uniform(size=(3000, 5))
    labels = rng.integers(0, 3, size=3000)
    wb, sb, mb = scoring_np.classify(
        X, labels, 3, ScoringConfig(median_method="bisect",
                                    compute_global_medians_from_data=True))
    wh, sh, mh = scoring_np.classify(
        X, labels, 3, ScoringConfig(median_method="hist",
                                    compute_global_medians_from_data=True))
    np.testing.assert_array_equal(wb, wh)
    np.testing.assert_allclose(mb, mh, atol=0)


def test_config_accepts_bisect():
    from cdrs_tpu.config import scoring_config_from_dict

    cfg = scoring_config_from_dict({"median_method": "bisect"})
    assert cfg.median_method == "bisect"
    with pytest.raises(ValueError, match="median_method"):
        scoring_config_from_dict({"median_method": "nope"})
