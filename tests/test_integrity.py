"""Data-integrity layer (ISSUE 9): silent-corruption faults, budgeted
background scrubbing, verified repair, detect-on-read, and the checkpoint
robustness satellites.

``CDRS_CHAOS_SEED`` varies the workload seeds — CI's integrity smoke step
sweeps it over three values so the invariants here are not single-seed
accidents.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.faults import (
    ClusterState,
    FaultEvent,
    FaultSchedule,
    RepairScheduler,
    ScrubConfig,
    Scrubber,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
NODES = ("dn1", "dn2", "dn3", "dn4")


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(
        GeneratorConfig(n_files=120, seed=41 + SEED, nodes=NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=420.0, seed=42 + SEED))
    return manifest, events


def _rf2_scoring():
    """Every category at rf >= 2 — no rf=1 singletons muddying the
    one-rotten-copy-is-recoverable invariants."""
    base = validated_scoring_config()
    return dataclasses.replace(
        base, replication_factors={c: max(2, r) for c, r in
                                   base.replication_factors.items()})


def _cfg(schedule=None, **kw):
    base = dict(window_seconds=60.0, kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config(), fault_schedule=schedule)
    base.update(kw)
    return ControllerConfig(**base)


def _strip(records):
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


def _toy_state(n=8, rf=2, seed=0, n_nodes=4):
    manifest = generate_population(
        GeneratorConfig(n_files=n, seed=seed, nodes=NODES[:n_nodes]))
    from cdrs_tpu.cluster import ClusterTopology, place_replicas

    placement = place_replicas(
        manifest, np.full(n, rf, dtype=np.int32),
        ClusterTopology(nodes=NODES[:n_nodes]), seed=0)
    return ClusterState(placement, manifest.size_bytes)


# -- corrupt fault events ----------------------------------------------------

def test_corrupt_spec_parse_and_roundtrip():
    s = FaultSchedule.from_specs(
        ["corrupt:dn2@3:0.25", "corrupt:dn1#17@4", "corrupt:dn3@5"])
    frac, pin, default = s.events[0], s.events[1], s.events[2]
    assert (frac.kind, frac.node, frac.window) == ("corrupt", "dn2", 3)
    assert frac.fail_prob == 0.25 and frac.file == -1
    assert pin.file == 17 and pin.node == "dn1"
    assert default.fail_prob == 0.1  # corrupt's default fraction
    # spec() and JSON both round-trip the file pin and the fraction.
    assert FaultSchedule.from_specs(
        [e.spec() for e in s.events]).events == s.events
    assert FaultSchedule.from_json(s.to_json()).events == s.events


def test_corrupt_event_validation():
    with pytest.raises(ValueError, match="file targeting"):
        FaultEvent(0, "crash", "dn1", file=3)
    with pytest.raises(ValueError, match="spans"):
        FaultSchedule.from_specs(["corrupt:dn2@3-5"])  # rot does not heal
    with pytest.raises(ValueError, match="node groups"):
        FaultEvent(0, "corrupt", "dn1+dn2")
    # A negative pin must not silently fall through to fraction mode.
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultSchedule.from_specs(["corrupt:dn2#-5@3"])
    # An out-of-range pin fails fast at apply time, naming the spec —
    # not an IndexError several windows into the run.
    st = _toy_state(n=8)
    with pytest.raises(ValueError, match="pins file 99999"):
        st.apply_event(FaultEvent(1, "corrupt", "dn2", file=99999))


def test_random_schedule_corrupt_rolls():
    a = FaultSchedule.random(NODES, 30, seed=SEED, corrupt_rate=0.3,
                             corrupt_frac=0.2)
    b = FaultSchedule.random(NODES, 30, seed=SEED, corrupt_rate=0.3,
                             corrupt_frac=0.2)
    assert a.events == b.events
    cor = [e for e in a if e.kind == "corrupt"]
    assert cor and all(e.fail_prob == 0.2 for e in cor)
    # corrupt_rate=0 (the default) draws no extra rolls: pre-existing
    # (nodes, n_windows, seed) schedules are bit-identical.
    plain = FaultSchedule.random(NODES, 30, seed=SEED)
    assert plain.events == FaultSchedule.random(NODES, 30,
                                                seed=SEED).events
    assert not any(e.kind == "corrupt" for e in plain)


# -- cluster state: the silent axis ------------------------------------------

def test_corruption_is_invisible_until_quarantined():
    st = _toy_state(rf=2)
    base_live = st.live_counts().copy()
    assert not st.has_corruption
    node = int(st.replica_map[0][st.replica_map[0] >= 0][0])
    assert st.corrupt_replica(0, node)
    assert not st.corrupt_replica(0, node)  # already rotten: no-op
    assert st.has_corruption
    # The blindness IS the threat model: live counts and the blind
    # durability tiers do not move.
    np.testing.assert_array_equal(st.live_counts(), base_live)
    assert not st.lost_mask().any()
    integ = st.integrity()
    assert integ == {"corrupt_copies": 1, "files_corrupt": 1,
                     "true_lost": 0}
    # Detection drops the copy: ordinary tiers now see the gap.
    st.quarantine(0, node)
    assert not st.has_corruption
    assert st.live_counts()[0] == base_live[0] - 1
    assert st.integrity()["corrupt_copies"] == 0


def test_corrupt_fraction_is_seeded_and_replayable():
    ev = FaultEvent(3, "corrupt", "dn2", fail_prob=0.5)
    a, b = _toy_state(n=60, seed=7), _toy_state(n=60, seed=7)
    a.apply_event(ev)
    b.apply_event(ev)
    np.testing.assert_array_equal(a.slot_corrupt, b.slot_corrupt)
    n_rot = int(a.slot_corrupt.sum())
    held = int((a.replica_map == a._nid("dn2")).any(axis=1).sum())
    assert 0 < n_rot < held  # a fraction, not all or nothing
    # A different window re-rolls the selection.
    c = _toy_state(n=60, seed=7)
    c.apply_event(FaultEvent(4, "corrupt", "dn2", fail_prob=0.5))
    assert (c.slot_corrupt != a.slot_corrupt).any()


def test_true_lost_sees_through_the_blind_tiers():
    st = _toy_state(n=10, rf=1)
    node = int(st.replica_map[2][st.replica_map[2] >= 0][0])
    st.corrupt_replica(2, node)
    # Blind tier: 1 live copy = fine.  Ground truth: the only copy is rot.
    assert not st.lost_mask()[2]
    assert st.true_lost_mask()[2]
    assert st.integrity()["true_lost"] == 1


def test_rot_survives_crash_but_not_decommission():
    st = _toy_state(rf=2)
    node = int(st.replica_map[1][st.replica_map[1] >= 0][0])
    name = NODES[node]
    st.corrupt_replica(1, node)
    st.apply_event(FaultEvent(0, "crash", name))
    assert st.corrupt_file_counts()[1] == 0  # down copies are not live...
    st.apply_event(FaultEvent(1, "recover", name))
    assert st.corrupt_file_counts()[1] == 1  # ...but the disk returns rotten
    st.apply_event(FaultEvent(2, "decommission", name))
    assert not st.has_corruption  # destroyed replicas take their rot along


def test_corruption_rides_the_checkpoint():
    st = _toy_state(rf=2)
    node = int(st.replica_map[3][st.replica_map[3] >= 0][0])
    st.corrupt_replica(3, node)
    st2 = _toy_state(rf=2)
    st2.load_state_arrays(st.state_arrays())
    np.testing.assert_array_equal(st2.slot_corrupt, st.slot_corrupt)
    assert st2.has_corruption
    # Pre-integrity checkpoints (no rot mask) load clean.
    arrays = {k: v for k, v in st.state_arrays().items()
              if k != "fault_slot_corrupt"}
    st3 = _toy_state(rf=2)
    st3.load_state_arrays(arrays)
    assert not st3.has_corruption


def test_verify_sources_quarantines_reachable_rot_only():
    st = _toy_state(rf=2)
    row = st.replica_map[0]
    n1, n2 = (int(x) for x in row[row >= 0][:2])
    st.corrupt_replica(0, n1)
    st.corrupt_replica(0, n2)
    # Straggler holder: the verification read is charged size/throughput.
    st.apply_event(FaultEvent(0, "degrade", NODES[n1], factor=0.25))
    # Partitioned holder: its rot is unreachable — stays latent.
    st.apply_event(FaultEvent(0, "partition", NODES[n2]))
    found, charge = st.verify_sources(0)
    assert found == 1
    assert charge == int(np.ceil(int(st.shard_bytes[0]) / 0.25))
    assert st.slot_corrupt[0].sum() == 1  # the stranded copy still rots
    st.apply_event(FaultEvent(1, "heal", NODES[n2]))
    found2, charge2 = st.verify_sources(0)
    assert found2 == 1 and charge2 == int(st.shard_bytes[0])
    assert not st.has_corruption


# -- the scrubber ------------------------------------------------------------

def test_scrub_cursor_paces_and_wraps():
    st = _toy_state(n=12, rf=2)
    budget = int(max(st.shard_bytes)) * 4
    sc = Scrubber(12, ScrubConfig(bytes_per_window=budget))
    seen_cursors = [sc.cursor]
    total_copies = 0
    wrapped = False
    for w in range(30):
        rep = sc.run_window(w, st)
        assert rep.bytes_used <= budget or rep.copies_verified == 1
        assert not rep.starved  # bytes_per_window-bound halt = pacing
        total_copies += rep.copies_verified
        seen_cursors.append(sc.cursor)
        if sc.cursor < seen_cursors[-2]:
            wrapped = True  # a full lap completed
            break
    assert wrapped
    assert any(b > a for a, b in zip(seen_cursors, seen_cursors[1:]))
    assert total_copies >= 12  # a lap verifies every file's copies


def test_scrub_detects_and_quarantines():
    st = _toy_state(n=10, rf=2)
    rot = []
    for f in (1, 4, 7):
        node = int(st.replica_map[f][st.replica_map[f] >= 0][0])
        st.corrupt_replica(f, node)
        rot.append((f, node))
    big = int(st.shard_bytes.sum()) * 4  # whole lap in one window
    sc = Scrubber(10, ScrubConfig(bytes_per_window=big))
    rep = sc.run_window(0, st)
    assert rep.corrupt_found == 3
    assert not st.has_corruption
    assert rep.files_verified == 10
    # The quarantined gaps are ordinary repair work now.
    assert (st.live_counts() < 2).sum() == 3


def test_scrub_starvation_is_about_the_shared_budget():
    st = _toy_state(n=12, rf=2)
    cfg = ScrubConfig(bytes_per_window=int(max(st.shard_bytes)) * 3)
    # Plenty of shared budget left: halting on bytes_per_window is pacing.
    sc = Scrubber(12, cfg)
    assert not sc.run_window(0, st, shared_left=10**12).starved
    # Repairs ate the shared budget down below the configured rate and
    # the scan halted on it: starved.
    sc2 = Scrubber(12, cfg)
    rep = sc2.run_window(0, st, shared_left=int(max(st.shard_bytes)))
    assert rep.starved and rep.bytes_used <= int(max(st.shard_bytes))
    # Nothing left at all: starved with zero work.
    sc3 = Scrubber(12, cfg)
    rep0 = sc3.run_window(0, st, shared_left=0)
    assert rep0.starved and rep0.copies_verified == 0
    assert sc3.cursor == 0  # cursor holds — next window re-scans


def test_scrub_hints_jump_the_queue():
    st = _toy_state(n=20, rf=2)
    node = int(st.replica_map[15][st.replica_map[15] >= 0][0])
    st.corrupt_replica(15, node)
    sc = Scrubber(20, ScrubConfig(
        bytes_per_window=int(max(st.shard_bytes)) * 3))
    sc.add_hints([15])
    rep = sc.run_window(0, st)  # the cursor alone would reach 15 late
    assert rep.hinted == 1 and rep.corrupt_found == 1
    assert sc.hints.size == 0
    assert not st.has_corruption


def test_scrubber_checkpoint_roundtrip():
    sc = Scrubber(50, ScrubConfig(bytes_per_window=1000))
    sc.cursor = 23
    sc.add_hints([7, 3, 7])
    arrays = sc.state_arrays()
    sc2 = Scrubber(50, ScrubConfig(bytes_per_window=1000))
    sc2.load_state_arrays(arrays)
    assert sc2.cursor == 23
    np.testing.assert_array_equal(sc2.hints, [3, 7])
    # Pre-scrub checkpoints: fresh lap, empty hints.
    sc3 = Scrubber(50, ScrubConfig(bytes_per_window=1000))
    sc3.load_state_arrays({})
    assert sc3.cursor == 0 and sc3.hints.size == 0


# -- verified repair ---------------------------------------------------------

def test_repair_refuses_corrupt_sources():
    """A file whose only reachable source is rot defers as no_source
    (with the rotten copy quarantined and the verification read charged)
    instead of propagating the rot into a fresh copy."""
    st = _toy_state(n=6, rf=2)
    row = st.replica_map[0]
    n1, n2 = (int(x) for x in row[row >= 0][:2])
    st.corrupt_replica(0, n1)
    st.apply_event(FaultEvent(0, "crash", NODES[n2]))  # clean copy down
    target = np.full(6, 2, dtype=np.int64)
    cat = np.zeros(6, dtype=np.int64)
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    rep = rs.schedule(0, st, target, cat)
    assert rep.corrupt_sources == 1
    assert rep.deferred_no_source >= 1
    assert rep.bytes_used > 0  # the wasted verification read is real
    assert not st.slot_corrupt[0].any()  # quarantined, not copied
    # The clean holder recovers: repair streams from it, file heals.
    st.apply_event(FaultEvent(1, "recover", NODES[n2]))
    rs.sync(st, target)
    rep2 = rs.schedule(1, st, target, cat)
    assert rep2.corrupt_sources == 0
    assert st.live_counts()[0] >= 2
    assert not st.true_lost_mask()[0]


def test_repair_with_no_corruption_is_flag_check_only():
    """The verified-read guard is one O(1) has_corruption check when no
    rot exists: repair reports are bit-identical to a pre-integrity
    pass."""
    st = _toy_state(n=20, rf=2)
    st.apply_event(FaultEvent(0, "crash", "dn2"))
    target = np.full(20, 2, dtype=np.int64)
    cat = np.zeros(20, dtype=np.int64)
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    rep = rs.schedule(0, st, target, cat)
    assert rep.corrupt_sources == 0
    assert rep.applied  # normal healing unobstructed


# -- detect-on-read (router) -------------------------------------------------

def _router(verify=True, n_nodes=3, policy="primary"):
    from cdrs_tpu.serve import ReadRouter, ServeConfig, SloSpec

    return ReadRouter(n_nodes, ServeConfig(
        policy=policy, seed=SEED, service_ms=1.0,
        slo=SloSpec(target_ms=50.0, availability=0.999),
        verify_reads=verify))


def _route(router, rm, corrupt, pid):
    e = len(pid)
    return router.route(
        rm, rm >= 0, np.ones(3), ts=np.arange(e, dtype=np.float64) * 10.0,
        pid=np.asarray(pid), client=np.full(e, -1, dtype=np.int64),
        window_seconds=60.0, rng=np.random.default_rng(SEED),
        slot_corrupt=corrupt)


def test_router_detects_redirects_and_reports():
    rm = np.asarray([[0, 1], [1, 2]], dtype=np.int32)
    corrupt = np.zeros((2, 2), dtype=bool)
    corrupt[0, 0] = True  # file 0's primary (node 0) is rot
    res = _route(_router(verify=True), rm, corrupt, [0, 0, 1])
    assert res.n_corrupt_detected == 2
    assert res.n_corrupt_served == 0
    np.testing.assert_array_equal(res.corrupt_pairs, [[0, 0]])
    # Both reads of file 0 were redirected to the clean copy on node 1.
    np.testing.assert_array_equal(res.server, [1, 1, 1])
    # The wasted rotten read costs one extra service time on the sample.
    clean = _route(_router(verify=True), rm, np.zeros((2, 2), bool),
                   [0, 0, 1])
    assert res.latency_ms[0] == pytest.approx(
        clean.latency_ms[0] + 1.0)


def test_router_refuses_when_no_clean_copy():
    rm = np.asarray([[0, 1], [1, 2]], dtype=np.int32)
    corrupt = np.zeros((2, 2), dtype=bool)
    corrupt[0] = True  # every copy of file 0 is rot
    res = _route(_router(verify=True), rm, corrupt, [0, 1])
    assert res.n_corrupt_detected == 1
    assert res.n_unavailable == 1  # refused, not served rotten
    assert res.server[0] == -1
    assert len(res.corrupt_pairs) == 1


def test_router_unverified_baseline_serves_garbage():
    rm = np.asarray([[0, 1], [1, 2]], dtype=np.int32)
    corrupt = np.zeros((2, 2), dtype=bool)
    corrupt[0, 0] = True
    res = _route(_router(verify=False), rm, corrupt, [0, 0, 1])
    assert res.n_corrupt_served == 2
    assert res.n_corrupt_detected == 0
    assert res.corrupt_pairs is None
    np.testing.assert_array_equal(res.server, [0, 0, 1])  # rot on the wire
    assert res.record_fields()["reads_corrupt_served"] == 2


def test_router_no_corruption_bit_identical():
    """slot_corrupt=None and an all-clean mask route identically —
    pre-integrity callers are unchanged."""
    rm = np.asarray([[0, 1], [1, 2]], dtype=np.int32)
    a = _route(_router(verify=True, policy="p2c"), rm, None, [0, 1, 0, 1])
    b = _route(_router(verify=True, policy="p2c"), rm,
               np.zeros((2, 2), bool), [0, 1, 0, 1])
    np.testing.assert_array_equal(a.server, b.server)
    np.testing.assert_array_equal(a.latency_ms, b.latency_ms)


# -- controller end to end ---------------------------------------------------

def test_scrub_requires_fault_schedule():
    with pytest.raises(ValueError, match="scrub requires"):
        _cfg(None, scrub=ScrubConfig(bytes_per_window=1000))
    with pytest.raises(ValueError, match="bytes_per_window"):
        ScrubConfig(bytes_per_window=0)


def test_controller_scrub_detects_and_heals(workload):
    """The flagship contract: rot lands silently, the scrubber finds all
    of it within one budget lap, verified repair re-replicates from the
    clean copies, and the run ends with zero latent rot and zero true
    losses."""
    manifest, events = workload
    sched = FaultSchedule.from_specs(["corrupt:dn2@1:1.0"])
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    res = ReplicationController(manifest, _cfg(
        sched, default_rf=2, scoring=_rf2_scoring(),
        scrub=ScrubConfig(bytes_per_window=int(sizes.sum()) * 3),
    )).run(events)
    summ = res.summary()
    integ = summ["integrity"]
    # The integrity record is POST-detection ground truth: with a
    # full-lap budget the same window that lands the rot also finds all
    # of it, so detections (not residual corrupt_copies) prove it landed.
    assert integ["detected_scrub"] > 0
    assert integ["corrupt_copies_final"] == 0    # all found
    assert integ["true_lost_final"] == 0         # all healed
    assert integ["scrub_starved_windows"] == 0
    assert summ["durability"]["lost_final"] == 0
    # Scrub accounting rode the records.
    scrubbed = [r["scrub"] for r in res.records if r.get("scrub")]
    assert scrubbed and all(s["cursor"] >= 0 for s in scrubbed)
    assert sum(s["corrupt_found"] for s in scrubbed) == \
        integ["detected_scrub"]


def test_unscrubbed_rot_plus_kill_loses_files(workload):
    """The baseline the bench contrasts: without scrubbing, rot stays
    latent until a node kill takes the clean copies — ground-truth
    losses and garbage served on the read path; the same schedule WITH
    scrubbing heals before the kill and loses nothing."""
    from cdrs_tpu.serve import ServeConfig, SloSpec

    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)

    def run(scrub_on, verify):
        sched = FaultSchedule.from_specs(
            ["corrupt:dn2@1:1.0", "crash:dn3@3"])
        serve = ServeConfig(policy="p2c", seed=0, service_ms=0.5,
                            slo=SloSpec(target_ms=10.0,
                                        availability=0.999),
                            verify_reads=verify)
        cfg = _cfg(sched, default_rf=2, scoring=_rf2_scoring(),
                   serve=serve,
                   scrub=ScrubConfig(bytes_per_window=int(sizes.sum()) * 3)
                   if scrub_on else None)
        res = ReplicationController(manifest, cfg).run(events)
        return res.summary()

    blind = run(scrub_on=False, verify=False)
    # Rot was served on the wire and the kill turned latent rot into
    # ground-truth loss.  (The blind tiers may partially catch up — the
    # repair pass verified-reads sources when healing the kill damage —
    # but they never OVERSTATE the ground truth.)
    assert blind["integrity"]["corrupt_reads_served"] > 0
    assert blind["integrity"]["true_lost_final"] >= 1
    assert blind["integrity"]["detected_read"] == 0  # verification was off
    assert blind["durability"]["lost_final"] <= \
        blind["integrity"]["true_lost_final"]

    healed = run(scrub_on=True, verify=True)
    assert healed["integrity"]["true_lost_final"] == 0
    assert healed["integrity"]["corrupt_reads_served"] == 0
    assert healed["integrity"]["detected_total"] > 0


def test_detect_on_read_feeds_scrub_hints(workload):
    """Serve-path detections quarantine the copy AND hint the scrubber;
    with a tiny scrub budget the hint queue is what gets verified."""
    from cdrs_tpu.serve import ServeConfig, SloSpec

    manifest, events = workload
    serve = ServeConfig(policy="p2c", seed=0, service_ms=0.5,
                        slo=SloSpec(target_ms=10.0, availability=0.999))
    sched = FaultSchedule.from_specs(["corrupt:dn1@1:1.0"])
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    # Budget sized so the hint queue drains a file or two per window but
    # the cursor lap crawls — hints must be what finds the rot.
    res = ReplicationController(manifest, _cfg(
        sched, default_rf=2, scoring=_rf2_scoring(), serve=serve,
        scrub=ScrubConfig(bytes_per_window=int(sizes.max()) * 3),
        max_bytes_per_window=None,
    )).run(events)
    integ = res.summary()["integrity"]
    assert integ["detected_read"] > 0
    # detected_read counts unique COPIES quarantined (the per-path
    # totals share one unit); reads_corrupt_detected counts READS — a
    # hot rotten copy hit many times in one batch bounds it from above.
    reads_detected = sum(r.get("reads_corrupt_detected") or 0
                         for r in res.records)
    assert 0 < integ["detected_read"] <= reads_detected
    hinted = sum((r.get("scrub") or {}).get("hinted", 0)
                 for r in res.records)
    assert hinted > 0  # the read detections became scrub work


def test_kill_resume_mid_scrub_bit_identical(tmp_path, workload):
    """A controller killed mid-scrub-lap (rot latent, cursor mid-flight,
    hints queued) resumes bit-identically — scrub cursor + hint queue +
    rot masks all ride the npz checkpoint."""
    from cdrs_tpu.serve import ServeConfig, SloSpec

    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)

    def mk():
        sched = FaultSchedule.from_specs(
            ["corrupt:dn2@1:0.6", "crash:dn3@2-3"])
        serve = ServeConfig(policy="p2c", seed=0, service_ms=0.5,
                            slo=SloSpec(target_ms=10.0,
                                        availability=0.999))
        return ReplicationController(manifest, _cfg(
            sched, default_rf=2, scoring=_rf2_scoring(), serve=serve,
            scrub=ScrubConfig(bytes_per_window=int(sizes.mean()) * 4),
            max_bytes_per_window=int(3 * sizes.max())))

    ref = mk().run(events)
    assert len(ref.records) >= 4
    ck = str(tmp_path / "scrub.npz")
    a = mk().run(events, checkpoint_path=ck, max_windows=2)  # mid-lap
    b = mk().run(events, checkpoint_path=ck)
    assert _strip(a.records) + _strip(b.records) == _strip(ref.records)
    np.testing.assert_array_equal(b.rf, ref.rf)


def test_scrub_checkpoint_flag_mismatch(tmp_path, workload):
    """A scrubbing controller cannot resume from a scrub-less checkpoint
    (and vice versa) — cursor/hint state would silently vanish."""
    manifest, events = workload
    ck = str(tmp_path / "c.npz")
    sched = ["corrupt:dn2@1:0.5"]
    ReplicationController(manifest, _cfg(
        FaultSchedule.from_specs(sched))).run(
        events, checkpoint_path=ck, max_windows=2)
    with pytest.raises(ValueError, match="scrub"):
        ReplicationController(manifest, _cfg(
            FaultSchedule.from_specs(sched),
            scrub=ScrubConfig(bytes_per_window=10**9))).run(
            events, checkpoint_path=ck)


# -- digests, auditor, CLI ---------------------------------------------------

def test_integrity_digest_shape_and_absence():
    from cdrs_tpu.obs.aggregate import integrity_digest

    assert integrity_digest([{"window": 0}]) is None  # pre-integrity
    rows = [
        {"window": 0,
         "integrity": {"corrupt_copies": 5, "files_corrupt": 5,
                       "true_lost": 1, "detected_scrub": 2,
                       "detected_read": 1, "detected_repair": 0},
         "scrub": {"bytes": 100, "copies_verified": 4,
                   "corrupt_found": 2, "starved": True, "cursor": 4},
         "reads_corrupt_served": 3},
        {"window": 1,
         "integrity": {"corrupt_copies": 1, "files_corrupt": 1,
                       "true_lost": 0, "detected_scrub": 1,
                       "detected_read": 0, "detected_repair": 1},
         "scrub": {"bytes": 80, "copies_verified": 3,
                   "corrupt_found": 1, "starved": False, "cursor": 7}},
    ]
    d = integrity_digest(rows)
    assert d["corrupt_copies_max"] == 5
    assert d["corrupt_copies_final"] == 1
    assert d["true_lost_max"] == 1 and d["true_lost_final"] == 0
    assert d["detected_total"] == 5
    assert d["detected_scrub"] == 3 and d["detected_read"] == 1
    assert d["corrupt_reads_served"] == 3
    assert d["scrub_bytes_total"] == 180
    assert d["scrub_starved_windows"] == 1


def test_auditor_flags_corruption_and_starvation():
    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.obs.audit import DecisionAuditor

    tel = Telemetry()
    aud = DecisionAuditor(np.ones(10, dtype=np.int64), 4)
    rec = {"integrity": {"corrupt_copies": 2, "true_lost": 0,
                         "detected_scrub": 1, "detected_read": 0,
                         "detected_repair": 0},
           "scrub": {"starved": True}}
    ev = aud.audit_window(tel, window=0, rec=rec, X=None, centroids=None,
                          rf=np.ones(10, dtype=np.int64),
                          cat=np.zeros(10, dtype=np.int64))
    assert "corruption_detected" in ev["flags"]
    assert "scrub_starved" in ev["flags"]
    assert ev["integrity"]["corrupt_copies"] == 2
    # No detections, no starvation: neither flag.
    ev2 = aud.audit_window(tel, window=1, rec={
        "integrity": {"corrupt_copies": 2, "true_lost": 0,
                      "detected_scrub": 0, "detected_read": 0,
                      "detected_repair": 0},
        "scrub": {"starved": False}},
        X=None, centroids=None, rf=np.ones(10, dtype=np.int64),
        cat=np.zeros(10, dtype=np.int64))
    assert "corruption_detected" not in ev2["flags"]
    assert "scrub_starved" not in ev2["flags"]


def test_summarize_and_report_render_integrity(tmp_path, workload,
                                               capsys):
    """`cdrs metrics summarize` prints the Integrity digest and `report`
    emits the Data-integrity section for an integrity stream — and both
    stay silent for pre-integrity streams."""
    from cdrs_tpu.obs.metrics_cli import summarize_events
    from cdrs_tpu.obs.report import render_html

    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    sched = FaultSchedule.from_specs(["corrupt:dn2@1:1.0"])
    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.obs.sink import JsonlSink

    mpath = str(tmp_path / "m.jsonl")
    with Telemetry(sink=JsonlSink(mpath)):
        ReplicationController(manifest, _cfg(
            sched, default_rf=2, scoring=_rf2_scoring(),
            scrub=ScrubConfig(bytes_per_window=int(sizes.sum()) * 3),
        )).run(events, metrics_path=mpath)
    rows = [json.loads(line) for line in
            open(mpath, encoding="utf-8") if line.strip()]
    import io

    out = io.StringIO()
    summarize_events(rows, out=out)
    text = out.getvalue()
    assert "Integrity:" in text
    assert "detected:" in text
    html = render_html(rows)
    assert "Data integrity (silent corruption)" in html
    # scrub.* and integrity.* counters landed in the stream.
    names = {r.get("name") for r in rows if r.get("kind") == "counter"}
    assert "scrub.corrupt_found" in names
    gauge_names = {r.get("name") for r in rows if r.get("kind") == "gauge"}
    assert "integrity.corrupt_copies" in gauge_names
    # Pre-integrity streams render without the section.
    plain = [r for r in rows if r.get("kind") != "window"]
    assert "Data integrity" not in render_html(plain)


def test_cli_chaos_corrupt_scrub_end_to_end(tmp_path, capsys):
    from cdrs_tpu.cli import main

    m = str(tmp_path / "m.csv")
    log = str(tmp_path / "a.log")
    assert main(["gen", "--n", "80", "--nodes", ",".join(NODES),
                 "--seed", str(50 + SEED), "--out_manifest", m]) == 0
    assert main(["simulate", "--manifest", m, "--out", log,
                 "--duration_seconds", "300", "--seed",
                 str(51 + SEED)]) == 0
    sched_out = str(tmp_path / "sched.json")
    capsys.readouterr()
    assert main(["chaos", "--manifest", m, "--access_log", log,
                 "--window_seconds", "60", "--scoring_config", "validated",
                 "--default_rf", "2", "--corrupt", "dn2@1:0.8",
                 "--scrub", "200000000", "--schedule_out",
                 sched_out]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "integrity" in out
    assert out["integrity"]["detected_scrub"] > 0
    rows = json.load(open(sched_out))
    assert {r["kind"] for r in rows} == {"corrupt"}
    assert rows[0]["fail_prob"] == 0.8
    # Pinned-file spec round-trips through the CLI too.
    assert main(["chaos", "--manifest", m, "--access_log", log,
                 "--window_seconds", "60", "--scoring_config", "validated",
                 "--corrupt", "dn1#3@1", "--max_windows", "2"]) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert "integrity" in out2


def test_cli_serve_corrupt_baseline_vs_verified(tmp_path, capsys):
    from cdrs_tpu.cli import main

    m = str(tmp_path / "m.csv")
    log = str(tmp_path / "a.log")
    main(["gen", "--n", "60", "--nodes", ",".join(NODES),
          "--seed", str(60 + SEED), "--out_manifest", m])
    main(["simulate", "--manifest", m, "--out", log,
          "--duration_seconds", "240", "--seed", str(61 + SEED)])
    capsys.readouterr()
    base = ["serve", "--manifest", m, "--access_log", log,
            "--window_seconds", "60", "--default_rf", "2",
            "--corrupt", "dn1@0:1.0"]
    assert main(base + ["--no_verify_reads"]) == 0
    blind = json.loads(capsys.readouterr().out)
    assert blind["reads_corrupt_served"] > 0
    assert main(base) == 0
    verified = json.loads(capsys.readouterr().out)
    assert verified["reads_corrupt_served"] == 0
    assert verified["reads_corrupt_detected"] > 0


# -- checkpoint fuzz (satellite) ---------------------------------------------

def _fuzz_corrupt_file(path: str, seed: int) -> None:
    """Truncate or bit-flip the file at a seeded random offset, then
    guarantee the damage actually broke the npz (fall back to a hard
    truncation when the flip landed in dead zip padding)."""
    from cdrs_tpu.utils.checkpoint import CheckpointError, load_state

    rng = np.random.default_rng(seed)
    size = os.path.getsize(path)
    offset = int(rng.integers(1, max(size - 1, 2)))
    if rng.random() < 0.5:
        with open(path, "r+b") as f:
            f.truncate(offset)
    else:
        with open(path, "r+b") as f:
            f.seek(offset)
            chunk = f.read(64)
            f.seek(offset)
            f.write(bytes(b ^ 0xFF for b in chunk))
    try:
        load_state(path)
    except CheckpointError:
        return
    with open(path, "r+b") as f:  # flip hit dead bytes: truncate instead
        f.truncate(max(size // 2, 1))
    with pytest.raises(CheckpointError):
        load_state(path)


@pytest.mark.slow
def test_checkpoint_fuzz_prev_fallback_across_modes(tmp_path, workload):
    """Fuzz the live checkpoint (truncate/bit-flip at seeded random
    offsets, seeds 0/1/2) across control/chaos/serve/storage flag
    combinations: every resume degrades to the retained ``.prev``
    last-good snapshot, increments ``degraded.checkpoint_fallback``, and
    re-converges bit-identically to the uninterrupted run."""
    import shutil

    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.serve import ServeConfig, SloSpec
    from cdrs_tpu.storage import StorageConfig, Strategy

    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    serve = ServeConfig(policy="p2c", seed=0, service_ms=0.5,
                        slo=SloSpec(target_ms=10.0, availability=0.999))
    scoring = _rf2_scoring()
    # ec(2,1) fits the 4-node toy topology (ec_archival's 6+3 does not).
    storage = StorageConfig(strategies={
        **{c: Strategy(kind="replicate", rf=r)
           for c, r in scoring.replication_factors.items()
           if c != "Archival"},
        "Archival": Strategy.from_spec("ec(2,1):cold")})
    combos = {
        "control": dict(),
        "chaos_scrub": dict(
            fault_schedule=FaultSchedule.from_specs(
                ["corrupt:dn2@1:0.5", "crash:dn3@2-3"]),
            scrub=ScrubConfig(bytes_per_window=int(sizes.mean()) * 4)),
        "chaos_serve": dict(
            fault_schedule=FaultSchedule.from_specs(["crash:dn2@1-2"]),
            serve=serve),
        "chaos_storage": dict(
            fault_schedule=FaultSchedule.from_specs(["crash:dn2@1-2"]),
            storage=storage),
    }
    for name, extra in combos.items():
        kw = dict(default_rf=2, scoring=scoring)
        kw.update(extra)
        sched = kw.pop("fault_schedule", None)

        def mk():
            return ReplicationController(manifest, _cfg(sched, **kw))

        ref = mk().run(events)
        ck = str(tmp_path / f"{name}.npz")
        mk().run(events, checkpoint_path=ck, max_windows=3)
        assert os.path.exists(ck + ".prev"), name
        pristine = ck + ".pristine"
        shutil.copyfile(ck, pristine)
        shutil.copyfile(ck + ".prev", pristine + ".prev")
        for seed in (0, 1, 2):
            shutil.copyfile(pristine, ck)
            shutil.copyfile(pristine + ".prev", ck + ".prev")
            _fuzz_corrupt_file(ck, seed)
            tel = Telemetry()
            with tel, pytest.warns(RuntimeWarning, match="last-good"):
                res = mk().run(events, checkpoint_path=ck)
            assert tel.counters.get("degraded.checkpoint_fallback") == 1, \
                (name, seed)
            # Bit-identical re-convergence from the one-older snapshot.
            np.testing.assert_array_equal(res.rf, ref.rf, err_msg=name)
            np.testing.assert_array_equal(res.category_idx,
                                          ref.category_idx, err_msg=name)
            assert _strip(res.records) == \
                _strip(ref.records)[-len(res.records):], (name, seed)
