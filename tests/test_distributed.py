"""Multi-host initialization layer (parallel/distributed.py).

A real DCN rendezvous needs multiple hosts; here we verify (a) the
single-process no-op contract in-process, and (b) an actual
jax.distributed.initialize rendezvous with a 1-process coordinator in a
SUBPROCESS (initialize mutates global runtime state the rest of the suite
must not inherit).
"""

import os
import subprocess
import sys

import pytest

pytest.importorskip("jax")

from cdrs_tpu.parallel.distributed import global_mesh, init_distributed, \
    mesh_axis_sizes


def test_single_process_is_noop():
    assert init_distributed() is False  # no coordinator env -> nothing to do


def test_env_gate_detects_cluster_markers(monkeypatch):
    """A multi-process launch must reach jax.distributed.initialize even
    without an explicit coordinator address: single-slice TPU pods publish
    the worker roster (TPU_WORKER_HOSTNAMES), SLURM steps/Open MPI publish world
    sizes — none of which set *COORDINATOR_ADDRESS (ADVICE r3, medium).
    Size-1 launches (1-chip TPU VM, 1-task SLURM job) must stay no-op."""
    import jax

    from cdrs_tpu.parallel import distributed as dist

    for var in dist._COORDINATOR_ENV_VARS + dist._WORLD_SIZE_ENV_VARS:
        monkeypatch.delenv(var, raising=False)

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))

    # No markers -> plain single-process run, initialize never called.
    monkeypatch.setattr(dist, "_initialized", False)
    assert dist.init_distributed() is False
    assert calls == []

    # Size-1 markers (this very axon box carries a 1-host
    # TPU_WORKER_HOSTNAMES): still single-process, still no-op.
    for var, val in (("TPU_WORKER_HOSTNAMES", "t1v-n-0"),
                     ("SLURM_STEP_NUM_TASKS", "1"), ("OMPI_COMM_WORLD_SIZE", "1")):
        monkeypatch.setenv(var, val)
        monkeypatch.setattr(dist, "_initialized", False)
        assert dist.init_distributed() is False, var
        assert calls == []
        monkeypatch.delenv(var)

    # World size > 1 -> must defer to jax's auto-detection.
    for var, val in (("TPU_WORKER_HOSTNAMES", "t1v-n-0,t1v-n-1"),
                     ("SLURM_STEP_NUM_TASKS", "4"), ("OMPI_COMM_WORLD_SIZE", "2"),
                     ("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")):
        monkeypatch.setenv(var, val)
        monkeypatch.setattr(dist, "_initialized", False)
        dist.init_distributed()
        assert calls[-1] == {}, var
        monkeypatch.delenv(var)

    # force=True skips the gate entirely (pod runtimes exposing only the
    # TPU metadata server set none of the env markers).
    n = len(calls)
    monkeypatch.setattr(dist, "_initialized", False)
    dist.init_distributed(force=True)
    assert len(calls) == n + 1

    monkeypatch.setattr(dist, "_initialized", False)


def test_global_mesh_spans_local_devices():
    mesh = global_mesh()
    assert mesh.devices.size == 8
    assert mesh_axis_sizes(mesh) == {"data": 8, "model": 1}
    mesh2 = global_mesh(n_model=2)
    assert mesh_axis_sizes(mesh2) == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="divisible"):
        global_mesh(n_model=3)


def test_explicit_coordinator_rendezvous_subprocess():
    """One-process 'cluster': initialize against a local coordinator, build
    the global mesh, run a psum across it."""
    code = """
import numpy as np
from cdrs_tpu.parallel.distributed import (global_mesh, init_distributed,
                                           mesh_axis_sizes)
init_distributed(coordinator_address="localhost:7723", num_processes=1,
                 process_id=0)
import jax
assert jax.process_count() == 1
mesh = global_mesh()
shape = mesh_axis_sizes(mesh)
from cdrs_tpu.ops.kmeans_jax import kmeans_jax
X = np.random.default_rng(0).normal(size=(256, 4)).astype(np.float32)
c, l = kmeans_jax(X, 3, seed=0, max_iter=5, mesh_shape=shape)
assert c.shape == (3, 4) and len(l) == 256
print("DIST_OK", shape)
"""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=os.pathsep.join(
                   [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert "DIST_OK" in out.stdout, out.stderr[-2000:]
    assert "'data': 8" in out.stdout
