"""Multi-host initialization layer (parallel/distributed.py).

A real DCN rendezvous needs multiple hosts; here we verify (a) the
single-process no-op contract in-process, and (b) an actual
jax.distributed.initialize rendezvous with a 1-process coordinator in a
SUBPROCESS (initialize mutates global runtime state the rest of the suite
must not inherit).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.parallel.distributed import global_mesh, init_distributed, \
    mesh_axis_sizes


def test_single_process_is_noop():
    assert init_distributed() is False  # no coordinator env -> nothing to do


def test_global_mesh_spans_local_devices():
    mesh = global_mesh()
    assert mesh.devices.size == 8
    assert mesh_axis_sizes(mesh) == {"data": 8, "model": 1}
    mesh2 = global_mesh(n_model=2)
    assert mesh_axis_sizes(mesh2) == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="divisible"):
        global_mesh(n_model=3)


def test_explicit_coordinator_rendezvous_subprocess():
    """One-process 'cluster': initialize against a local coordinator, build
    the global mesh, run a psum across it."""
    code = """
import numpy as np
from cdrs_tpu.parallel.distributed import (global_mesh, init_distributed,
                                           mesh_axis_sizes)
init_distributed(coordinator_address="localhost:7723", num_processes=1,
                 process_id=0)
import jax
assert jax.process_count() == 1
mesh = global_mesh()
shape = mesh_axis_sizes(mesh)
from cdrs_tpu.ops.kmeans_jax import kmeans_jax
X = np.random.default_rng(0).normal(size=(256, 4)).astype(np.float32)
c, l = kmeans_jax(X, 3, seed=0, max_iter=5, mesh_shape=shape)
assert c.shape == (3, 4) and len(l) == 256
print("DIST_OK", shape)
"""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=os.pathsep.join(
                   [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert "DIST_OK" in out.stdout, out.stderr[-2000:]
    assert "'data': 8" in out.stdout
