"""Fault injection & self-healing (cdrs_tpu/faults/ + controller wiring):
schedules, cluster-state durability tiers, repair under the shared churn
budget, kill/resume mid-fault bit-identity, degraded modes.

``CDRS_CHAOS_SEED`` varies the workload/schedule seeds — CI's chaos smoke
step sweeps it over three values so the invariants here are not
single-seed accidents.
"""

import json
import os

import numpy as np
import pytest

from cdrs_tpu.config import (
    CATEGORIES,
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.faults import (
    ClusterState,
    FaultEvent,
    FaultSchedule,
    RepairScheduler,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(
        GeneratorConfig(n_files=150, seed=21 + SEED, nodes=NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=600.0, seed=22 + SEED))
    return manifest, events


def _cfg(schedule=None, **kw):
    base = dict(window_seconds=120.0, kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config(), fault_schedule=schedule)
    base.update(kw)
    return ControllerConfig(**base)


def _strip(records):
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


# -- schedule ----------------------------------------------------------------

def test_schedule_specs_spans_and_ordering():
    s = FaultSchedule.from_specs(
        ["crash:dn2@3-5", "flaky:dn1@2-4:0.7", "decommission:dn3@1"])
    assert [e.spec() for e in s.for_window(3)] == ["crash:dn2@3"]
    assert s.for_window(6)[0].kind == "recover"       # span end + 1
    assert s.for_window(2)[0].fail_prob == 0.7
    assert s.for_window(5) == (FaultEvent(5, "unflaky", "dn1"),)
    assert s.max_window == 6
    # Within a window, recover sorts before crash (KINDS order).
    s2 = FaultSchedule([FaultEvent(1, "crash", "dn1"),
                        FaultEvent(1, "recover", "dn2")])
    assert [e.kind for e in s2.for_window(1)] == ["recover", "crash"]


def test_schedule_json_roundtrip_and_validation():
    s = FaultSchedule.from_specs(["crash:dn2@3", "flaky:dn1@2:0.25"])
    assert FaultSchedule.from_json(s.to_json()).events == s.events
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultSchedule.from_specs(["crash@dn2:3"])
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultSchedule.from_specs(["crash:dn2@x"])     # non-integer window
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultSchedule.from_specs(["crash:dn2@3:0.5"])  # prob on non-flaky
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSchedule.from_specs(["explode:dn2@3"])
    with pytest.raises(ValueError, match="spans"):
        FaultSchedule.from_specs(["decommission:dn1@2-4"])
    with pytest.raises(ValueError, match="outside the topology"):
        s.validate_nodes(("dn1",))


def test_schedule_random_is_deterministic_and_keeps_one_node():
    a = FaultSchedule.random(NODES, 40, seed=SEED, crash_rate=0.5,
                             recover_windows=(3, 8))
    b = FaultSchedule.random(NODES, 40, seed=SEED, crash_rate=0.5,
                             recover_windows=(3, 8))
    assert a.events == b.events and len(a) > 0
    # Replay: at least one node up at every window.
    up = {n: True for n in NODES}
    for w in range(a.max_window + 1):
        for e in a.for_window(w):
            up[e.node] = e.kind != "crash" if e.kind in ("crash", "recover") \
                else up[e.node]
        assert any(up.values())
    # Every crash eventually recovers (recoveries scheduled past the
    # n_windows horizon are flushed, not dropped).
    assert all(up.values())
    crashes = sum(1 for e in a if e.kind == "crash")
    recovers = sum(1 for e in a if e.kind == "recover")
    assert crashes == recovers > 0


# -- cluster state -----------------------------------------------------------

def _toy_state(n=6, rf=2, seed=0):
    manifest = generate_population(
        GeneratorConfig(n_files=n, seed=seed, nodes=NODES[:4]))
    from cdrs_tpu.cluster import ClusterTopology, place_replicas

    placement = place_replicas(
        manifest, np.full(n, rf, dtype=np.int32),
        ClusterTopology(nodes=NODES[:4]), seed=0)
    return ClusterState(placement, manifest.size_bytes)


def test_state_crash_recover_decommission():
    st = _toy_state(rf=2)
    base = st.live_counts().copy()
    assert (base == 2).all()
    st.apply_event(FaultEvent(0, "crash", "dn1"))
    down = st.live_counts()
    held = (st.replica_map == 0).any(axis=1)
    np.testing.assert_array_equal(down, base - held.astype(np.int32))
    st.apply_event(FaultEvent(1, "recover", "dn1"))
    np.testing.assert_array_equal(st.live_counts(), base)  # replicas return
    st.apply_event(FaultEvent(2, "decommission", "dn1"))
    assert not (st.replica_map == 0).any()                 # destroyed
    st.apply_event(FaultEvent(3, "recover", "dn1"))        # permanent
    assert st.n_available == 3
    with pytest.raises(ValueError, match="unknown node"):
        st.apply_event(FaultEvent(0, "crash", "dn9"))


def test_state_durability_tiers_match_bruteforce():
    """Property-style: vectorized tiers == per-file brute force over random
    fault states."""
    rng = np.random.default_rng(100 + SEED)
    for trial in range(5):
        st = _toy_state(n=40, rf=1 + int(rng.integers(0, 3)),
                        seed=int(rng.integers(0, 1000)))
        target = rng.integers(1, 5, size=40).astype(np.int64)
        cat = rng.integers(-1, 4, size=40).astype(np.int64)
        for i in np.flatnonzero(rng.random(4) < 0.5):
            st.apply_event(FaultEvent(0, "crash", NODES[:4][i]))
        d = st.durability(target, cat, CATEGORIES)
        avail = st.n_available
        lost = at_risk = under = 0
        for f in range(40):
            row = st.replica_map[f]
            live = sum(1 for x in row if x >= 0 and st.node_up[x])
            eff = min(int(target[f]), avail)
            if live == 0:
                lost += 1
            elif live == 1 and eff >= 2:
                at_risk += 1
            elif 2 <= live < eff:
                under += 1
        assert (d["lost"], d["at_risk"], d["under_replicated"]) == \
            (lost, at_risk, under)
        tier_sum = sum(v for c in d["per_category"].values()
                       for v in c.values())
        assert tier_sum == lost + at_risk + under


def test_state_checkpoint_roundtrip():
    st = _toy_state(rf=2)
    st.apply_event(FaultEvent(0, "crash", "dn2"))
    st.apply_event(FaultEvent(0, "flaky", "dn3", fail_prob=0.4))
    st.add_replica(0, st.pick_repair_target(0))
    arrays = st.state_arrays()
    st2 = _toy_state(rf=2)
    st2.load_state_arrays(arrays)
    np.testing.assert_array_equal(st2.replica_map, st.replica_map)
    np.testing.assert_array_equal(st2.node_up, st.node_up)
    np.testing.assert_array_equal(st2.node_fail_prob, st.node_fail_prob)
    np.testing.assert_array_equal(st2.node_bytes, st.node_bytes)


# -- repair + controller self-healing ---------------------------------------

def test_controller_heals_after_kill(workload):
    """Kill one node mid-run: files drop below target, the repair planner
    re-replicates them back, and durability accounting sees both sides.
    A min-rf-2 scoring table keeps every file copyable (an rf=1 category
    trivially loses a dead node's singletons — covered separately by
    test_lost_files_heal_only_after_recover)."""
    import dataclasses

    manifest, events = workload
    base = validated_scoring_config()
    scoring = dataclasses.replace(
        base, replication_factors={c: max(2, r) for c, r in
                                   base.replication_factors.items()})
    sched = FaultSchedule.from_specs(["crash:dn2@2"])
    res = ReplicationController(
        manifest, _cfg(sched, default_rf=2, scoring=scoring)).run(events)
    kill = [r for r in res.records if r["window"] == 2][0]
    assert kill["fault_events"] == ["crash:dn2@2"]
    assert kill["durability"]["nodes_up"] == len(NODES) - 1
    d = res.summary()["durability"]
    assert d["repair_moves_total"] > 0 and d["repair_bytes_total"] > 0
    last = res.records[-1]["durability"]
    assert last["under_replicated"] == 0 and last["at_risk"] == 0
    # default_rf=2 + min 2 live before the kill: nothing can be lost.
    assert d["files_lost_max"] == 0


def test_lost_files_heal_only_after_recover(workload):
    """Files whose every replica is on the dead node are LOST (no copy
    source) until the node recovers; then the repair planner heals them."""
    manifest, events = workload
    sched = FaultSchedule.from_specs(["crash:dn2@1-2"])
    # default_rf=1: some files' single replica lives on dn2.
    res = ReplicationController(
        manifest, _cfg(sched, drift_threshold=10.0)).run(events)
    by_w = {r["window"]: r for r in res.records}
    lost_during = by_w[1]["durability"]["lost"]
    if lost_during == 0:
        pytest.skip("no singleton replica landed on dn2 at this seed")
    assert by_w[1]["repair_deferred_no_source"] >= 0
    assert by_w[3]["durability"]["lost"] == 0      # recovered at window 3
    assert res.records[-1]["durability"]["under_replicated"] == 0


def test_repair_and_migration_share_budget(workload):
    """Repair traffic preempts drift migrations for the SAME byte budget:
    per-window repair + migration bytes never exceed it, and in the
    post-kill windows repairs consume budget migrations wanted."""
    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    budget = int(3 * sizes.max())  # tight but above any single move
    sched = FaultSchedule.from_specs(["crash:dn2@2"])
    res = ReplicationController(
        manifest, _cfg(sched, default_rf=2, max_bytes_per_window=budget,
                       hysteresis_windows=0)).run(events)
    assert all(r["repair_bytes"] + r["bytes_migrated"] <= budget
               for r in res.records)
    post = [r for r in res.records if r["window"] >= 2]
    assert sum(r["repair_bytes"] for r in post) > 0
    # The shared budget actually contended: some window deferred work.
    assert any(r["deferred_budget"] or r["repair_deferred_budget"]
               for r in res.records)


def test_flaky_node_retries_with_backoff():
    """Copies to a flaky node fail deterministically, back off
    exponentially, and rotate to another target on retry."""
    st = _toy_state(n=8, rf=1, seed=3)
    st.apply_event(FaultEvent(0, "flaky", "dn1", fail_prob=1.0))
    st.apply_event(FaultEvent(0, "flaky", "dn2", fail_prob=1.0))
    st.apply_event(FaultEvent(0, "flaky", "dn3", fail_prob=1.0))
    st.apply_event(FaultEvent(0, "flaky", "dn4", fail_prob=1.0))
    target = np.full(8, 2, dtype=np.int64)
    cat = np.zeros(8, dtype=np.int64)
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    assert rs.backlog
    r0 = rs.schedule(0, st, target, cat)
    assert r0.failed > 0 and not r0.applied
    attempts = {f: t.attempts for f, t in rs.backlog.items()}
    assert all(a == 1 for a in attempts.values())
    # Backoff: window+2^1 — nothing eligible at the next window.
    r1 = rs.schedule(1, st, target, cat)
    assert r1.deferred_backoff == len(rs.backlog) and not r1.failed
    # Heal the cluster: all repairs land once nodes stop failing.
    for n in NODES[:4]:
        st.apply_event(FaultEvent(2, "unflaky", n))
    r2 = rs.schedule(2, st, target, cat)
    assert len(r2.applied) == 8 and not rs.backlog
    assert (st.live_counts() == 2).all()


def test_flaky_rolls_are_stateless_deterministic():
    from cdrs_tpu.faults.repair import _fail_roll

    a = [_fail_roll(SEED, w, f, t) for w in range(3) for f in range(3)
         for t in range(3)]
    b = [_fail_roll(SEED, w, f, t) for w in range(3) for f in range(3)
         for t in range(3)]
    assert a == b
    assert all(0.0 <= x < 1.0 for x in a)
    assert len(set(a)) > 20  # rolls vary across (window, file, attempt)
    # Copies of the same file within one window draw INDEPENDENT rolls.
    assert _fail_roll(SEED, 1, 2, 0, copy=0) != _fail_roll(SEED, 1, 2, 0,
                                                           copy=1)


def test_kill_resume_mid_fault_bit_identical(tmp_path, workload):
    """A controller killed mid-outage (fault applied, repairs in flight)
    and resumed from its checkpoint reproduces the uninterrupted run's
    full record stream — fault state + repair backlog ride the snapshot."""
    manifest, events = workload
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)

    def mk():
        sched = FaultSchedule.from_specs(
            ["crash:dn2@1-2", "flaky:dn3@2-3:0.8"])
        return ReplicationController(
            manifest, _cfg(sched, default_rf=2,
                           max_bytes_per_window=int(3 * sizes.max())))

    ref = mk().run(events)
    assert len(ref.records) >= 4
    ck = str(tmp_path / "chaos.npz")
    a = mk().run(events, checkpoint_path=ck, max_windows=2)  # mid-outage
    b = mk().run(events, checkpoint_path=ck)
    assert _strip(a.records) + _strip(b.records) == _strip(ref.records)
    np.testing.assert_array_equal(b.rf, ref.rf)
    np.testing.assert_array_equal(b.category_idx, ref.category_idx)


def test_fault_checkpoint_mode_mismatch(tmp_path, workload):
    """A fault-mode checkpoint must not load into a fault-less controller
    (and vice versa) — the replica map would silently vanish."""
    manifest, events = workload
    ck = str(tmp_path / "c.npz")
    sched = FaultSchedule.from_specs(["crash:dn2@1"])
    ReplicationController(manifest, _cfg(sched)).run(
        events, checkpoint_path=ck, max_windows=2)
    with pytest.raises(ValueError, match="faults"):
        ReplicationController(manifest, _cfg()).run(
            events, checkpoint_path=ck)


def test_controller_corrupt_checkpoint_falls_back_to_prev(tmp_path,
                                                          workload):
    """Degraded mode: a truncated checkpoint degrades to the retained
    .prev snapshot (one interval older) and the deterministic loop
    re-converges to the uninterrupted run's exact final state."""
    manifest, events = workload
    ref = ReplicationController(manifest, _cfg()).run(events)
    ck = str(tmp_path / "ctl.npz")
    ReplicationController(manifest, _cfg()).run(
        events, checkpoint_path=ck, max_windows=3)
    assert os.path.exists(ck + ".prev")
    with open(ck, "r+b") as f:
        f.truncate(64)
    with pytest.warns(RuntimeWarning, match="last-good"):
        res = ReplicationController(manifest, _cfg()).run(
            events, checkpoint_path=ck)
    np.testing.assert_array_equal(res.rf, ref.rf)
    np.testing.assert_array_equal(res.category_idx, ref.category_idx)
    # The fallback PROMOTED the good snapshot over the corrupt path (and
    # the run re-checkpointed): neither file is corrupt afterwards, so a
    # crash right after the fallback cannot brick resume.
    from cdrs_tpu.utils.checkpoint import load_state

    load_state(ck)
    load_state(ck + ".prev")
    # Deleting the checkpoint means START OVER, even with .prev retained
    # (the delete-to-reset contract of the stale-checkpoint message).
    os.unlink(ck)
    assert os.path.exists(ck + ".prev")
    fresh = ReplicationController(manifest, _cfg())
    res2 = fresh.run(events, checkpoint_path=ck)
    assert res2.records and res2.records[0]["window"] == 0


def test_degraded_kernel_falls_back_to_numpy(workload, monkeypatch):
    """jax kernel failure mid-loop degrades to the numpy backend (one
    warning + degraded.kernel_fallback counter) instead of crashing."""
    pytest.importorskip("jax")
    from cdrs_tpu.models.replication import ReplicationPolicyModel
    from cdrs_tpu.obs import Telemetry

    manifest, events = workload
    ctl = ReplicationController(manifest, _cfg(backend="jax"))

    def boom(self, X, init_centroids=None):
        raise RuntimeError("device lost")

    monkeypatch.setattr(ctl._model_full, "run",
                        boom.__get__(ctl._model_full))
    monkeypatch.setattr(ctl._model_warm, "run",
                        boom.__get__(ctl._model_warm))
    tel = Telemetry()
    with tel, pytest.warns(RuntimeWarning, match="numpy backend"):
        res = ctl.run(events)
    assert tel.counters.get("degraded.kernel_fallback", 0) >= 1
    assert any(r.get("degraded_kernel") for r in res.records)
    assert (res.category_idx >= 0).any()  # a plan was still produced
    assert isinstance(ctl._fallback_models[False],
                      ReplicationPolicyModel)


def test_degraded_recluster_direct(workload):
    """DIRECT coverage of ``_degraded_recluster`` (previously only
    exercised through a full monkeypatched controller run): counter per
    invocation, one-time warning, lazy per-variant fallback-model cache,
    and a real numpy ClusterDecision out."""
    pytest.importorskip("jax")
    import warnings

    from cdrs_tpu.models.replication import ClusterDecision
    from cdrs_tpu.obs import Telemetry

    manifest, _ = workload
    ctl = ReplicationController(manifest, _cfg(backend="jax"))
    rng = np.random.default_rng(SEED)
    X = rng.uniform(size=(len(manifest), 5)).astype(np.float32)

    tel = Telemetry()
    with tel:
        with pytest.warns(RuntimeWarning, match="numpy backend"):
            dec = ctl._degraded_recluster(
                False, X, None, RuntimeError("device lost"))
        assert isinstance(dec, ClusterDecision)
        assert dec.labels.shape == (len(manifest),)
        assert (dec.category_idx >= 0).all()
        full_model = ctl._fallback_models[False]
        # Second failure (warm variant): counter again, NO second warning,
        # a separate warm fallback model is built and cached.
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            dec2 = ctl._degraded_recluster(
                True, X, dec.centroids, RuntimeError("device lost again"))
        assert isinstance(dec2, ClusterDecision)
        assert ctl._fallback_models[True] is not full_model
        # Third failure reuses the cached model object — no rebuild.
        ctl._degraded_recluster(False, X, None, RuntimeError("again"))
        assert ctl._fallback_models[False] is full_model
    assert tel.counters["degraded.kernel_fallback"] == 3


# -- scheduler load validation (satellite) -----------------------------------

def test_migration_scheduler_rejects_malformed_arrays():
    from cdrs_tpu.control import MigrationScheduler

    s = MigrationScheduler(10)
    good = s.state_arrays()
    with pytest.raises(ValueError, match="missing scheduler arrays"):
        MigrationScheduler(10).load_state_arrays(
            {k: v for k, v in good.items() if k != "sched_priority"})
    bad = dict(good)
    bad["sched_file_index"] = np.asarray([3, 99], dtype=np.int64)
    bad["sched_rf_old"] = np.asarray([1, 1], dtype=np.int64)
    bad["sched_rf_new"] = np.asarray([2, 2], dtype=np.int64)
    bad["sched_cat_old"] = np.asarray([0, 0], dtype=np.int64)
    bad["sched_cat_new"] = np.asarray([1, 1], dtype=np.int64)
    bad["sched_bytes_moved"] = np.asarray([5, 5], dtype=np.int64)
    bad["sched_priority"] = np.asarray([0.0, 0.0])
    with pytest.raises(ValueError, match="outside"):
        MigrationScheduler(10).load_state_arrays(bad)
    bad2 = dict(bad)
    bad2["sched_file_index"] = np.asarray([1, 2], dtype=np.int64)
    bad2["sched_priority"] = np.asarray([0.0])  # length mismatch
    with pytest.raises(ValueError, match="shape"):
        MigrationScheduler(10).load_state_arrays(bad2)
    bad3 = dict(good)
    bad3["sched_last_moved"] = np.zeros(10, dtype=np.float64)
    with pytest.raises(ValueError, match="not integral"):
        MigrationScheduler(10).load_state_arrays(bad3)


# -- placement rf-cap satellite ----------------------------------------------

def test_placement_rf_cap_warns_and_counts():
    import warnings

    from cdrs_tpu.cluster import (ClusterTopology, place_replicas,
                                  reset_rf_cap_warning)
    from cdrs_tpu.obs import Telemetry

    manifest = generate_population(GeneratorConfig(n_files=30, seed=1))
    rf = np.full(30, 4, dtype=np.int32)  # Archival rf=4, 3-node topology
    reset_rf_cap_warning()
    try:
        tel = Telemetry()
        with tel:
            with pytest.warns(UserWarning, match="capped at the node"):
                place_replicas(manifest, rf,
                               ClusterTopology(("dn1", "dn2", "dn3")))
            assert tel.counters["placement.rf_capped"] == 30
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # one-time: no second warn
                place_replicas(manifest, rf,
                               ClusterTopology(("dn1", "dn2", "dn3")))
            assert tel.counters["placement.rf_capped"] == 60
            # The latch is resettable (test isolation): re-arm and it
            # fires again within the same process.
            reset_rf_cap_warning()
            with pytest.warns(UserWarning, match="capped at the node"):
                place_replicas(manifest, rf,
                               ClusterTopology(("dn1", "dn2", "dn3")))
    finally:
        reset_rf_cap_warning()


# -- cdrs chaos CLI ----------------------------------------------------------

def test_cli_chaos_end_to_end(tmp_path, capsys):
    from cdrs_tpu.cli import main

    m = str(tmp_path / "m.csv")
    log = str(tmp_path / "a.log")
    assert main(["gen", "--n", "80", "--nodes", ",".join(NODES),
                 "--seed", str(30 + SEED), "--out_manifest", m]) == 0
    assert main(["simulate", "--manifest", m, "--out", log,
                 "--duration_seconds", "300", "--seed",
                 str(31 + SEED)]) == 0
    sched_out = str(tmp_path / "sched.json")
    capsys.readouterr()
    assert main(["chaos", "--manifest", m, "--access_log", log,
                 "--window_seconds", "60", "--scoring_config", "validated",
                 "--default_rf", "2", "--kill", "dn2@1-2",
                 "--flaky", "dn3@2-2:0.5", "--schedule_out",
                 sched_out]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "durability" in out and out["windows"] >= 4
    # dn2 recovers at window 3: nothing stays lost or under-replicated.
    assert out["durability"]["lost_final"] == 0
    assert out["durability"]["under_replicated_final"] == 0
    rows = json.load(open(sched_out))
    assert {r["kind"] for r in rows} == {"crash", "recover", "flaky",
                                         "unflaky"}
    # Replay the written schedule via --schedule: same durability story.
    assert main(["chaos", "--manifest", m, "--access_log", log,
                 "--window_seconds", "60", "--scoring_config", "validated",
                 "--default_rf", "2", "--schedule", sched_out]) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out2["durability"]["fault_events"] == \
        out["durability"]["fault_events"]
    assert out2["final_plan_hash"] == out["final_plan_hash"]


def test_cli_chaos_requires_a_fault(tmp_path, capsys):
    from cdrs_tpu.cli import main

    m = str(tmp_path / "m.csv")
    log = str(tmp_path / "a.log")
    main(["gen", "--n", "20", "--seed", "1", "--out_manifest", m])
    main(["simulate", "--manifest", m, "--out", log,
          "--duration_seconds", "30", "--seed", "2"])
    capsys.readouterr()
    assert main(["chaos", "--manifest", m, "--access_log", log]) == 1
    assert "at least one fault" in capsys.readouterr().err


# -- chaos bench harness -----------------------------------------------------

def test_chaos_bench_small_scenario(tmp_path):
    """The kill-one-node bench end to end at toy scale: recovery bounded,
    zero lost, budget respected, artifact JSON round-trips."""
    from cdrs_tpu.benchmarks.chaos_bench import run_chaos_bench

    out = run_chaos_bench(n_files=120, seed=7 + SEED, duration=720.0,
                          n_windows=8, kill_window=3, k=8,
                          resume_check=False, overhead=False)
    assert out["criteria"]["recovered_within_run"]
    assert out["criteria"]["zero_files_lost"]
    assert out["criteria"]["budget_respected"]
    assert out["recovery"]["windows_to_full_re_replication"] is not None
    assert out["recovery"]["repair_bytes_total"] > 0
    p = tmp_path / "cb.json"
    p.write_text(json.dumps(out))
    assert json.loads(p.read_text())["criteria"] == out["criteria"]
