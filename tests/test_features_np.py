"""Golden tests for the NumPy feature backend against hand-computed fixtures.

Exercises every formula and edge case of SURVEY.md §2.2: per-op counts,
locality (incl. the zero-access 1.0 rule), two-level concurrency, age from
observation_end, write_ratio = writes/mean(writes), and the degenerate
min-max normalization.
"""

import numpy as np

from cdrs_tpu.features.numpy_backend import compute_features, minmax_normalize
from cdrs_tpu.io.events import EventLog, Manifest


def make_manifest(n=3, nodes=("dn1", "dn2")):
    return Manifest(
        paths=[f"/f{i}" for i in range(n)],
        creation_ts=np.array([0.0, 100.0, 200.0][:n]),
        primary_node_id=np.array([0, 1, 0][:n], dtype=np.int32),
        size_bytes=np.array([10, 20, 30][:n], dtype=np.int64),
        category=["hot", "moderate", "archival"][:n],
        nodes=list(nodes),
    )


def make_events(rows, manifest):
    """rows: list of (ts, path_idx, op(0/1), client_idx)."""
    return EventLog(
        ts=np.array([r[0] for r in rows], dtype=np.float64),
        path_id=np.array([r[1] for r in rows], dtype=np.int32),
        op=np.array([r[2] for r in rows], dtype=np.int8),
        client_id=np.array([r[3] for r in rows], dtype=np.int32),
        clients=list(manifest.nodes),
    )


def test_counts_locality_concurrency_age():
    m = make_manifest()
    # file 0 (primary dn1=0): 4 events, 1 write; 3 local.
    #   seconds 10: two events -> concurrency 2
    # file 1 (primary dn2=1): 2 events, both writes, 0 local
    # file 2: no events -> zero counters, locality 1.0
    rows = [
        (10.1, 0, 0, 0),
        (10.9, 0, 0, 0),
        (11.5, 0, 1, 0),
        (20.0, 0, 0, 1),
        (15.0, 1, 1, 0),
        (30.0, 1, 1, 0),
    ]
    ev = make_events(rows, m)
    t = compute_features(m, ev)

    af, age, wr, loc, conc = t.raw.T
    np.testing.assert_allclose(af, [4, 2, 0])
    np.testing.assert_allclose(t.writes, [1, 2, 0])
    np.testing.assert_allclose(t.reads, [3, 0, 0])
    np.testing.assert_allclose(loc, [3 / 4, 0.0, 1.0])
    np.testing.assert_allclose(conc, [2, 1, 0])
    # observation_end = max ts = 30.0; creation 0/100/200
    np.testing.assert_allclose(age, [30.0, -70.0, -170.0])
    # mean writes = (1+2+0)/3 = 1.0 -> write_ratio = writes
    np.testing.assert_allclose(wr, [1.0, 2.0, 0.0])


def test_write_ratio_zero_mean_guard():
    m = make_manifest()
    ev = make_events([(5.0, 0, 0, 0)], m)  # one READ, zero writes anywhere
    t = compute_features(m, ev)
    # mean(writes)=0 -> forced to 1.0 (compute_features.py:64-65)
    np.testing.assert_allclose(t.raw[:, 2], [0.0, 0.0, 0.0])


def test_unknown_paths_dropped_but_extend_observation_end():
    m = make_manifest(n=1)
    ev = EventLog(
        ts=np.array([10.0, 99.0]),
        path_id=np.array([0, -1], dtype=np.int32),  # second event: unknown path
        op=np.array([0, 0], dtype=np.int8),
        client_id=np.array([0, 0], dtype=np.int32),
        clients=list(m.nodes),
    )
    t = compute_features(m, ev)
    np.testing.assert_allclose(t.raw[0, 0], 1.0)       # only 1 counted access
    np.testing.assert_allclose(t.raw[0, 1], 99.0)      # age uses max over raw log


def test_empty_log_uses_wallclock_and_locality_one():
    m = make_manifest()
    ev = make_events([], m)
    t = compute_features(m, ev, observation_end=1000.0)
    np.testing.assert_allclose(t.raw[:, 0], 0)          # access_freq
    np.testing.assert_allclose(t.raw[:, 3], 1.0)        # locality rule
    np.testing.assert_allclose(t.raw[:, 1], [1000.0, 900.0, 800.0])
    # constant columns normalize to all-zeros (compute_features.py:86-88)
    np.testing.assert_allclose(t.norm[:, 0], 0.0)
    np.testing.assert_allclose(t.norm[:, 3], 0.0)


def test_minmax_normalize():
    col = np.array([1.0, 3.0, 2.0])
    np.testing.assert_allclose(minmax_normalize(col), [0.0, 1.0, 0.5])
    np.testing.assert_allclose(minmax_normalize(np.full(4, 7.0)), 0.0)


def test_norm_columns_in_unit_interval():
    rng = np.random.default_rng(0)
    m = make_manifest()
    rows = [(float(rng.random() * 50), int(rng.integers(0, 3)),
             int(rng.integers(0, 2)), int(rng.integers(0, 2))) for _ in range(200)]
    t = compute_features(m, make_events(rows, m))
    assert t.norm.min() >= 0.0 and t.norm.max() <= 1.0
    # non-degenerate columns hit both 0 and 1
    af = t.norm[:, 0]
    assert af.min() == 0.0 and af.max() == 1.0


def test_concurrency_bucket_edges():
    m = make_manifest(n=1)
    # 10.99 and 11.01 are different floor-buckets; 11.01/11.99 share one.
    ev = make_events([(10.99, 0, 0, 0), (11.01, 0, 0, 0), (11.99, 0, 0, 0)], m)
    t = compute_features(m, ev)
    np.testing.assert_allclose(t.raw[0, 4], 2.0)


def test_seeded_manifest_unseeded_simulator_sane_ages():
    """A seeded manifest (anchored to the fixed epoch, ~2023) driven by an
    UNSEEDED simulator must not report multi-year ages: the simulation
    window anchors to the manifest's latest creation timestamp, not wall
    clock (r3 code-review finding on the seeded-workload change)."""
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.features.numpy_backend import compute_features
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=50, seed=5))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=60.0, seed=None))   # unseeded on purpose
    table = compute_features(manifest, events)
    age_col = table.raw_names.index("age_seconds")
    ages = np.asarray(table.raw)[:, age_col]
    assert ages.min() >= 0.0
    assert ages.max() <= 366 * 86400 + 120.0
