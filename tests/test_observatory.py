"""Observatory layer (ISSUE 3): XLA cost capture (obs/xprof), decision-
quality audit (obs/audit), HTML report + live watch (obs/report,
obs/metrics_cli, obs/sink.iter_events)."""

import io
import json
import os
import re
import threading
import time

import numpy as np
import pytest

from cdrs_tpu.obs import JsonlSink, Telemetry, iter_events, read_events
from cdrs_tpu.obs.metrics_cli import (
    _prom_name,
    main as metrics_main,
    watch,
)
from cdrs_tpu.obs.report import render_html

# -- canned stream shared by the report tests --------------------------------

CANNED = [
    {"kind": "meta", "t": 1000.0, "run": {"python": "3.10.0",
                                          "jax_device_kind": "TPU v5e"}},
    {"kind": "span", "name": "fold", "id": 2, "parent": 1, "t": 1000.1,
     "dur": 0.25, "run": "r1"},
    {"kind": "span", "name": "window", "id": 1, "parent": None, "t": 1000.0,
     "dur": 1.5, "run": "r1"},
    {"kind": "counter", "name": "controller.windows", "t": 1000.2,
     "delta": 1.0, "value": 2.0, "run": "r1"},
    {"kind": "gauge", "name": "audit.silhouette", "t": 1000.3, "value": 0.41,
     "run": "r1"},
    {"kind": "gauge", "name": "audit.silhouette", "t": 1000.4, "value": 0.47,
     "run": "r1"},
    {"kind": "hist", "name": "controller.total.seconds", "t": 1000.5,
     "value": 0.8, "run": "r1"},
    {"kind": "hist", "name": "controller.total.seconds", "t": 1000.6,
     "value": 1.2, "run": "r1"},
    {"kind": "xla", "event": "compile", "kernel": "kmeans_jax_full",
     "sig": 42, "t": 1000.7, "lower_seconds": 0.1, "compile_seconds": 1.75,
     "flops": 2.0e12, "bytes_accessed": 4.0e10, "temp_bytes": 1 << 20,
     "argument_bytes": 1 << 22, "output_bytes": 1 << 14, "run": "r1"},
    {"kind": "xla", "event": "exec", "kernel": "kmeans_jax_full", "sig": 42,
     "t": 1000.8, "seconds": 0.05, "run": "r1"},
    {"kind": "kmeans_iter", "kernel": "kmeans_jax_full", "call": 1,
     "step": 0, "inertia": 40.0, "shift": 1.0, "backend": "jax", "k": 4,
     "run": "r1"},
    {"kind": "kmeans_iter", "kernel": "kmeans_jax_full", "call": 1,
     "step": 1, "inertia": 22.0, "shift": 0.0, "backend": "jax", "k": 4,
     "run": "r1"},
    {"kind": "audit", "window": 0, "t": 1000.9, "silhouette": 0.41,
     "davies_bouldin": 1.2, "category_entropy": 0.8,
     "replication_bytes": 1000, "locality": 0.7, "flags": [], "run": "r1"},
    {"kind": "audit", "window": 1, "t": 1001.0, "silhouette": 0.30,
     "davies_bouldin": 1.6, "category_entropy": 0.7, "population_tv": 0.2,
     "replication_bytes": 1400, "replication_bytes_delta": 400,
     "locality": 0.6, "flags": ["drift_no_gain", "budget_saturated"],
     "run": "r1"},
    {"kind": "window", "window": 0, "n_events": 100, "recluster": True,
     "recluster_mode": "full", "drift": None, "moves_applied": 5,
     "bytes_migrated": 5000, "locality_after": 0.7, "run": "r1"},
    {"kind": "window", "window": 1, "n_events": 120, "recluster": True,
     "recluster_mode": "warm", "drift": 0.21, "moves_applied": 3,
     "bytes_migrated": 3000, "locality_after": 0.6, "run": "r1"},
]

_GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                       "report_golden.html")


# -- xprof -------------------------------------------------------------------

def test_instrumented_call_captures_and_matches():
    pytest.importorskip("jax")
    import jax
    import jax.numpy as jnp

    from cdrs_tpu.obs import xprof

    xprof.clear_cache()
    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.arange(12.0, dtype=jnp.float32).reshape(3, 4)
    plain = fn(x)
    events = []
    with Telemetry() as tel:
        tel._emit = events.append
        out1 = xprof.instrumented_call("toy", fn, (x,), signature=("toy",))
        out2 = xprof.instrumented_call("toy", fn, (x,), signature=("toy",))
    assert float(out1) == float(plain) == float(out2)
    xla = [e for e in events if e.get("kind") == "xla"]
    kinds = [(e["event"]) for e in xla]
    assert kinds == ["compile", "exec"]  # second call: cached, no re-capture
    compile_ev = xla[0]
    assert compile_ev["kernel"] == "toy"
    assert compile_ev["compile_seconds"] > 0
    assert compile_ev.get("flops", 0) > 0
    assert tel.counters["xla.compiles.toy"] == 1


def test_instrumented_call_off_without_instrument():
    pytest.importorskip("jax")
    import jax
    import jax.numpy as jnp

    from cdrs_tpu.obs import xprof

    xprof.clear_cache()
    fn = jax.jit(lambda x: x * 2)
    x = jnp.ones((4,))
    out = xprof.instrumented_call("toy2", fn, (x,), signature=("toy2",))
    np.testing.assert_array_equal(np.asarray(out), 2 * np.ones(4))
    assert not xprof._COMPILED  # nothing captured with telemetry off


def test_kmeans_xprof_events_and_parity(tmp_path):
    pytest.importorskip("jax")
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(7)
    X = rng.normal(size=(173, 5)).astype(np.float32)
    ref = kmeans_jax_full(X, 3, seed=0, max_iter=5)
    p = str(tmp_path / "x.jsonl")
    with Telemetry(JsonlSink(p)):
        got = kmeans_jax_full(X, 3, seed=0, max_iter=5)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               atol=1e-6)
    xla = [e for e in read_events(p) if e.get("kind") == "xla"
           and e.get("kernel") == "kmeans_jax_full"]
    assert {e["event"] for e in xla} == {"compile", "exec"}
    comp = next(e for e in xla if e["event"] == "compile")
    for key in ("flops", "bytes_accessed", "compile_seconds",
                "temp_bytes", "output_bytes"):
        assert key in comp, key


# -- report ------------------------------------------------------------------

def test_report_html_golden():
    """The HTML report of a canned stream is byte-stable (deterministic
    rendering is what makes it reviewable as a diff)."""
    html = render_html(CANNED, title="golden")
    with open(_GOLDEN, encoding="utf-8") as f:
        golden = f.read()
    assert html == golden, (
        "report HTML drifted from tests/data/report_golden.html; if the "
        "change is intentional, regenerate with: python -c \"import json;"
        "from tests.test_observatory import CANNED, _GOLDEN;"
        "from cdrs_tpu.obs.report import render_html;"
        "open(_GOLDEN,'w').write(render_html(CANNED, title='golden'))\"")


def test_report_html_structure():
    html = render_html(CANNED, title="structure")
    for required in (
        "<!doctype html",
        "Span tree (wall-clock, aggregated)",
        "XLA kernel costs (roofline)",
        "Decision-quality audit timeline",
        "Controller windows",
        "KMeans convergence traces",
        "drift_no_gain",
        "class=\"spark\"",          # sparklines present
        "kmeans_jax_full",
        "% of attainable",          # peaks known (TPU v5e in canned meta)
    ):
        assert required in html, required
    # flags are never color-alone: the label text rides the status color
    assert "⚠ drift_no_gain" in html
    # one audit row per window, last-wins dedup intact
    assert html.count("✓ clean") == 1


def test_report_cli_roundtrip(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in CANNED),
                 encoding="utf-8")
    out = tmp_path / "r.html"
    assert metrics_main(["report", str(p), "-o", str(out)]) == 0
    html = out.read_text(encoding="utf-8")
    assert "Decision-quality audit timeline" in html
    # default output path: <file>.html
    assert metrics_main(["report", str(p)]) == 0
    assert (tmp_path / "s.jsonl.html").exists()


def test_summarize_shows_roofline_and_audit(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in CANNED),
                 encoding="utf-8")
    assert metrics_main(["summarize", str(p)]) == 0
    text = capsys.readouterr().out
    assert "XLA kernel costs (roofline)" in text
    assert "compile=1.75s" in text
    # 2e12 flops / 0.05 s = 40 TF/s achieved; v5e peaks known -> verdict
    assert "% of" in text and "bound" in text
    assert "Audit: 2 windows" in text
    assert "drift_no_gain" in text


# -- iter_events / watch -----------------------------------------------------

def test_iter_events_buffers_partial_line(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"a": 1}\n{"b": 2', encoding="utf-8")
    # non-follow: the torn tail is skipped (read_events contract)
    assert [e for e in iter_events(str(p))] == [{"a": 1}]
    # follow: the partial line is buffered until its newline arrives
    got = []

    def consume():
        for e in iter_events(str(p), follow=True, poll=0.01,
                             stop=lambda: len(got) >= 2):
            got.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.08)
    with open(p, "a", encoding="utf-8") as f:
        f.write('2}\n')
    t.join(timeout=5)
    assert not t.is_alive()
    assert got == [{"a": 1}, {"b": 22}]


def test_iter_events_waits_for_missing_file(tmp_path):
    p = tmp_path / "late.jsonl"
    got = []

    def consume():
        for e in iter_events(str(p), follow=True, poll=0.01,
                             stop=lambda: len(got) >= 1):
            got.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    with open(p, "w", encoding="utf-8") as f:
        f.write('{"x": 9}\n')
    t.join(timeout=5)
    assert got == [{"x": 9}]


def test_iter_events_recovers_from_truncation(tmp_path):
    """rm + fresh producer while a watcher follows: the stale offset must
    reset instead of reading b'' forever."""
    p = tmp_path / "s.jsonl"
    p.write_text('{"a": 1}\n{"a": 2}\n', encoding="utf-8")
    got = []

    def consume():
        for e in iter_events(str(p), follow=True, poll=0.02,
                             stop=lambda: len(got) >= 3):
            got.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    os.remove(p)
    p.write_text('{"b": 9}\n', encoding="utf-8")  # recreated, smaller
    t.join(timeout=5)
    assert got == [{"a": 1}, {"a": 2}, {"b": 9}]


def test_instrumented_call_concurrent_first_calls_compile_once():
    pytest.importorskip("jax")
    import jax
    import jax.numpy as jnp

    from cdrs_tpu.obs import xprof

    xprof.clear_cache()
    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.arange(20.0).reshape(4, 5)
    events = []
    with Telemetry() as tel:
        tel._emit = events.append
        threads = [threading.Thread(
            target=lambda: xprof.instrumented_call(
                "race", fn, (x,), signature=("race",)))
            for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        compiles = [e for e in events if e.get("kind") == "xla"
                    and e.get("event") == "compile"]
        assert len(compiles) == 1
        assert tel.counters["xla.compiles.race"] == 1


def test_watch_once_renders_dashboard(tmp_path):
    p = tmp_path / "w.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in CANNED),
                 encoding="utf-8")
    buf = io.StringIO()
    assert watch(str(p), once=True, out=buf) == 0
    text = buf.getvalue()
    assert "windows: 2" in text
    assert "audit" in text
    assert "flags:" in text and "budget_saturated" in text


# -- audit: controller integration + schema ----------------------------------

def test_controller_emits_audit_event_per_window(tmp_path):
    from cdrs_tpu.config import (GeneratorConfig, KMeansConfig,
                                 SimulatorConfig, validated_scoring_config)
    from cdrs_tpu.control import ControllerConfig, ReplicationController
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=120, seed=21))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=240.0, seed=22))
    cfg = ControllerConfig(window_seconds=120.0,
                           kmeans=KMeansConfig(k=6, seed=42),
                           scoring=validated_scoring_config())
    mp = str(tmp_path / "m.jsonl")
    with Telemetry(JsonlSink(mp), meta=False):
        res = ReplicationController(manifest, cfg).run(events,
                                                       metrics_path=mp)
    assert len(res.records) >= 2
    stream = read_events(mp)
    audits = [e for e in stream if e.get("kind") == "audit"]
    # one audit record per processed window, window indices aligned
    assert [a["window"] for a in audits] == \
        [r["window"] for r in res.records]
    for a in audits:
        for key in ("category_entropy", "replication_bytes", "flags"):
            assert key in a, key
        assert isinstance(a["flags"], list)
        assert 0.0 <= a["category_entropy"] <= 1.0
    # windows that computed a feature snapshot carry the geometry metrics
    assert any("silhouette" in a and "davies_bouldin" in a for a in audits)
    sil = [a["silhouette"] for a in audits if "silhouette" in a]
    assert all(-1.0 <= s <= 1.0 for s in sil)
    # the same stream also grew audit gauges
    assert any(e.get("kind") == "gauge"
               and e["name"] == "audit.silhouette" for e in stream)


def test_audit_off_flag(tmp_path):
    from cdrs_tpu.config import (GeneratorConfig, KMeansConfig,
                                 SimulatorConfig, validated_scoring_config)
    from cdrs_tpu.control import ControllerConfig, ReplicationController
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=60, seed=23))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=120.0, seed=24))
    cfg = ControllerConfig(window_seconds=120.0,
                           kmeans=KMeansConfig(k=4, seed=42),
                           scoring=validated_scoring_config())
    mp = str(tmp_path / "m.jsonl")
    with Telemetry(JsonlSink(mp), meta=False, audit=False):
        ReplicationController(manifest, cfg).run(events, metrics_path=mp)
    assert not [e for e in read_events(mp) if e.get("kind") == "audit"]


def test_audit_flags_fire():
    from cdrs_tpu.obs.audit import AuditConfig, DecisionAuditor

    class Cap:
        def __init__(self):
            self.events = []
            self.counters = {}

        def _emit(self, e):
            self.events.append(e)

        def gauge(self, *a):
            pass

        def counter_inc(self, name, delta=1.0):
            self.counters[name] = self.counters.get(name, 0) + delta

    rng = np.random.default_rng(0)
    tight = np.concatenate([rng.normal(0, 0.02, (40, 3)),
                            rng.normal(1, 0.02, (40, 3))])
    loose = rng.uniform(-1, 2, (80, 3))
    cents = np.array([[0.0, 0, 0], [1.0, 1, 1]])
    sizes = np.full(80, 100)
    cap = Cap()
    aud = DecisionAuditor(sizes, 4, AuditConfig(budget_windows=2))
    rf = np.ones(80, dtype=np.int64)
    cat = np.zeros(80, dtype=np.int64)
    base = {"recluster": False, "deferred_budget": 0}
    aud.audit_window(cap, window=0, rec=dict(base), X=tight,
                     centroids=cents, rf=rf, cat=cat)
    # window 1: re-cluster ran, quality collapsed, budget deferred
    aud.audit_window(cap, window=1,
                     rec={"recluster": True, "deferred_budget": 3,
                          "locality_before": 0.8, "locality_after": 0.5},
                     X=loose, centroids=cents, rf=rf, cat=cat)
    # window 2: budget still deferred -> saturation streak reached
    e2 = aud.audit_window(cap, window=2,
                          rec={"recluster": False, "deferred_budget": 1},
                          X=loose, centroids=cents, rf=rf, cat=cat)
    flags1 = cap.events[1]["flags"]
    assert "drift_no_gain" in flags1
    assert "locality_regressed" in flags1
    assert "budget_saturated" in e2["flags"]
    assert cap.counters["audit.flags.drift_no_gain"] == 1


def test_silhouette_proxy_orders_quality():
    from cdrs_tpu.obs.audit import silhouette_db_proxy

    rng = np.random.default_rng(1)
    cents = np.array([[0.0, 0], [5.0, 5]])
    tight = np.concatenate([rng.normal(0, 0.05, (50, 2)),
                            rng.normal(5, 0.05, (50, 2))])
    loose = np.concatenate([rng.normal(0, 2.5, (50, 2)),
                            rng.normal(5, 2.5, (50, 2))])
    sil_t, db_t = silhouette_db_proxy(tight, cents)
    sil_l, db_l = silhouette_db_proxy(loose, cents)
    assert sil_t > sil_l          # tighter clusters score higher
    assert db_t < db_l            # ...and lower Davies-Bouldin
    assert sil_t > 0.9
    # degenerate inputs never raise
    assert silhouette_db_proxy(tight[:0], cents) == (0.0, 0.0)
    assert silhouette_db_proxy(tight, cents[:1]) == (0.0, 0.0)


# -- prometheus name escaping (satellite) ------------------------------------

_PROM_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")


def test_prom_name_digit_leading_and_punctuation():
    # digit-leading event name stays valid with AND without the namespace
    assert _prom_name("9p99.latency") == "cdrs_9p99_latency"
    assert _prom_name("9p99.latency", prefix="") == "_9p99_latency"
    for name in ("9p99.latency", "jit.recompiles.kmeans_jax_full",
                 "a b/c-d{e}", "@#!", "0", "p50"):
        for prefix in ("cdrs_", ""):
            got = _prom_name(name, prefix=prefix)
            assert _PROM_NAME_RE.fullmatch(got), (name, prefix, got)


def test_read_events_survives_torn_multibyte_tail(tmp_path):
    """A writer killed mid-multi-byte-character must not poison the
    stream: the mangled final line is skipped, not a UnicodeDecodeError."""
    p = tmp_path / "t.jsonl"
    good = json.dumps({"name": "π"}, ensure_ascii=False).encode("utf-8")
    torn = json.dumps({"name": "catégorie"},
                      ensure_ascii=False).encode("utf-8")
    p.write_bytes(good + b"\n" + torn[:-3])  # cut inside the é sequence
    events = read_events(str(p))
    assert events == [{"name": "π"}]
    assert list(iter_events(str(p))) == [{"name": "π"}]


def test_sig_id_stable_across_processes(tmp_path):
    """xla event sig ids key cross-run aggregation, so they must be
    content hashes, not the per-process-salted builtin hash()."""
    import subprocess
    import sys as _sys

    code = ("from cdrs_tpu.obs.xprof import _sig_id;"
            "print(_sig_id('kern', ((128, 5), 'float32', ('a', 1))))")
    outs = {
        subprocess.run(
            [_sys.executable, "-c", code], text=True, capture_output=True,
            env={**os.environ, "PYTHONHASHSEED": seed,
                 "PYTHONPATH": os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__)))},
        ).stdout.strip()
        for seed in ("1", "2")
    }
    assert len(outs) == 1 and outs != {""}


def test_exit_meta_carries_jax_fields(tmp_path):
    """Telemetry stamps run metadata again at exit: activation happens
    before a command imports jax, so only the exit stamp can carry the
    device kind the roofline peak lookup needs."""
    pytest.importorskip("jax")
    p = str(tmp_path / "t.jsonl")
    with Telemetry(JsonlSink(p)):
        pass
    metas = [e for e in read_events(p) if e.get("kind") == "meta"]
    assert len(metas) == 2
    assert "jax_device_kind" in metas[-1]["run"]  # jax imported by now
    from cdrs_tpu.obs.aggregate import collect

    # collect() takes the last stamp — the enriched one
    assert "jax_device_kind" in collect(read_events(p))["meta"]


def test_roofline_partial_peak_override():
    from cdrs_tpu.obs.aggregate import collect, roofline_rows

    digest = collect(CANNED)  # meta names TPU v5e (819 GB/s table bw)
    [row] = roofline_rows(digest, peak_flops=100e12, peak_gbps=None)
    # the device table must fill the side the user did not override
    assert row["bound"] in ("memory", "compute")
    assert "attainable_gflops" in row


def test_sink_utf8_roundtrip(tmp_path):
    p = str(tmp_path / "u.jsonl")
    with JsonlSink(p) as s:
        s.emit({"name": "catégorie.ñ", "note": "π≈3.14159"})
    e = read_events(p)[0]
    assert e["name"] == "catégorie.ñ" and e["note"] == "π≈3.14159"
    # the bytes on disk are utf-8 regardless of platform default
    raw = open(p, "rb").read().decode("utf-8")
    assert "catégorie" in raw
