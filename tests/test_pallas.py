"""Pallas fused assign+reduce kernel — interpret-mode parity on CPU.

On real TPU the same kernel compiles via Mosaic (exercised by bench/dev runs);
tests force interpret=True so CI needs no TPU.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax.numpy as jnp

from cdrs_tpu.ops.kmeans_np import assign_labels
from cdrs_tpu.ops.pallas_kernels import lloyd_assign_reduce_pallas


@pytest.mark.parametrize("n,d,k,n_valid", [
    (2048, 5, 7, 2048),      # pipeline shape (d=5), k not lane-aligned
    (2048, 32, 128, 1999),   # padding rows masked via n_valid
])
def test_pallas_assign_reduce_parity(n, d, k, n_valid):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    c = x[:k].copy()

    lab, sums, counts = lloyd_assign_reduce_pallas(
        jnp.asarray(x), jnp.asarray(c), n_valid=n_valid, interpret=True)

    lab_np = assign_labels(x.astype(np.float64), c.astype(np.float64))
    w = np.zeros(n)
    w[:n_valid] = 1.0
    sums_np = np.stack(
        [np.bincount(lab_np, weights=x[:, j] * w, minlength=k) for j in range(d)],
        axis=1)
    counts_np = np.bincount(lab_np, weights=w, minlength=k)

    assert (np.asarray(lab) == lab_np).mean() == 1.0
    np.testing.assert_allclose(np.asarray(sums), sums_np, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), counts_np, atol=0)


@pytest.mark.parametrize("n,d,k,n_valid", [
    (2048, 5, 7, 2048),      # pipeline shape (d=5), k not lane-aligned
    (2048, 32, 128, 1999),   # zero-padded tail excluded via n_valid
])
def test_pallas_feature_major_parity(n, d, k, n_valid):
    """The (d, n) feature-major kernel matches the golden numpy stats.

    Columns past n_valid are zeroed — the kernel contract (every production
    caller zero-pads; the wrapper corrects their count, not a per-tile mask).
    """
    from cdrs_tpu.ops.pallas_kernels import lloyd_assign_reduce_pallas_t

    rng = np.random.default_rng(3)
    x = rng.normal(size=(n, d)).astype(np.float32)
    x[n_valid:] = 0.0
    c = x[:k].copy()

    lab, sums, counts = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x).T, jnp.asarray(c), n_valid=n_valid, interpret=True,
        tile_cols=1024)  # 2 tiles: exercises cross-tile accumulation

    lab_np = assign_labels(x.astype(np.float64), c.astype(np.float64))
    w = np.zeros(n)
    w[:n_valid] = 1.0
    sums_np = np.stack(
        [np.bincount(lab_np, weights=x[:, j] * w, minlength=k) for j in range(d)],
        axis=1)
    counts_np = np.bincount(lab_np, weights=w, minlength=k)

    assert (np.asarray(lab)[:n_valid] == lab_np[:n_valid]).mean() == 1.0
    np.testing.assert_allclose(np.asarray(sums), sums_np, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), counts_np, atol=0)


def test_pallas_feature_major_enforce_pad():
    """enforce_pad=True restores correct stats for NON-zero pad columns.

    Without the guard, garbage past n_valid silently corrupts sums/counts
    (the documented API failure mode); with it, results match the
    zero-padded call exactly.
    """
    from cdrs_tpu.ops.pallas_kernels import lloyd_assign_reduce_pallas_t

    rng = np.random.default_rng(7)
    n, d, k, n_valid = 2048, 8, 16, 1500
    x = rng.normal(size=(n, d)).astype(np.float32)
    c = x[:k].copy()
    x_dirty = x.copy()
    x_dirty[n_valid:] = 99.0  # violates the zero-pad contract

    _, sums_ref, counts_ref = lloyd_assign_reduce_pallas_t(
        jnp.asarray(np.where(np.arange(n)[:, None] < n_valid, x, 0.0)
                    .astype(np.float32)).T,
        jnp.asarray(c), n_valid=n_valid, interpret=True, tile_cols=512)
    _, sums_g, counts_g = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x_dirty).T, jnp.asarray(c), n_valid=n_valid,
        interpret=True, tile_cols=512, enforce_pad=True)

    np.testing.assert_allclose(np.asarray(sums_g), np.asarray(sums_ref),
                               atol=0)
    np.testing.assert_allclose(np.asarray(counts_g), np.asarray(counts_ref),
                               atol=0)


def test_enforce_pad_env_read_once_and_warns_on_flip(monkeypatch):
    """CDRS_TPU_ENFORCE_PAD is read ONCE at import; flipping it afterwards
    is ignored with a one-time RuntimeWarning (it used to do nothing
    silently — traced kernels replay without the guard)."""
    import warnings

    from cdrs_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "_enforce_pad_warned", False)
    flipped = "0" if pk._ENFORCE_PAD else "1"
    monkeypatch.setenv("CDRS_TPU_ENFORCE_PAD", flipped)
    with pytest.warns(RuntimeWarning, match="IGNORED"):
        assert pk._enforce_pad_env() is pk._ENFORCE_PAD
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call must NOT warn again
        assert pk._enforce_pad_env() is pk._ENFORCE_PAD
    # Matching value: no warning, flag returned.
    monkeypatch.setattr(pk, "_enforce_pad_warned", False)
    monkeypatch.setenv("CDRS_TPU_ENFORCE_PAD",
                       "1" if pk._ENFORCE_PAD else "0")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert pk._enforce_pad_env() is pk._ENFORCE_PAD


def test_enforce_pad_flip_warns_via_kmeans_entry(monkeypatch):
    """The flip warning fires from the EAGER Lloyd entry even when every
    kernel shape is already compiled (traced wrappers replay without
    re-running their Python)."""
    from cdrs_tpu.ops import pallas_kernels as pk
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    X = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
    kmeans_jax_full(X, 4, max_iter=1, seed=0)  # trace + compile first
    monkeypatch.setattr(pk, "_enforce_pad_warned", False)
    monkeypatch.setenv("CDRS_TPU_ENFORCE_PAD",
                       "0" if pk._ENFORCE_PAD else "1")
    with pytest.warns(RuntimeWarning, match="IGNORED"):
        kmeans_jax_full(X, 4, max_iter=1, seed=0)


def test_pallas_feature_major_no_labels():
    from cdrs_tpu.ops.pallas_kernels import lloyd_assign_reduce_pallas_t

    rng = np.random.default_rng(4)
    x = rng.normal(size=(1024, 8)).astype(np.float32)
    c = x[:5].copy()
    lab, sums, counts = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x).T, jnp.asarray(c), n_valid=1024, interpret=True,
        with_labels=False, tile_cols=512)
    assert lab is None
    lab2, sums2, counts2 = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x).T, jnp.asarray(c), n_valid=1024, interpret=True,
        tile_cols=512)
    np.testing.assert_allclose(np.asarray(sums), np.asarray(sums2), atol=0)
    np.testing.assert_allclose(np.asarray(counts), np.asarray(counts2), atol=0)


def test_pallas_update_strategy_in_kmeans():
    """update='pallas' (interpret on CPU) matches the matmul strategy."""
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(1)
    X = rng.normal(size=(2048, 8)).astype(np.float32)
    init = X[:6].copy()
    c1, l1, *_ = kmeans_jax_full(X, 6, seed=0, max_iter=20, tol=0.0,
                                 init_centroids=init, update="matmul")
    c2, l2, *_ = kmeans_jax_full(X, 6, seed=0, max_iter=20, tol=0.0,
                                 init_centroids=init, update="pallas")
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-4)
    assert (np.asarray(l1) == np.asarray(l2)).mean() > 0.999
