"""Parity tests: JAX scoring backend vs the NumPy golden model."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import ScoringConfig
from cdrs_tpu.ops import scoring_np
from cdrs_tpu.ops.scoring_jax import (
    classify_jax,
    compute_cluster_medians_hist_jax,
    compute_cluster_medians_jax,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    X = rng.uniform(size=(500, 5))
    labels = rng.integers(0, 4, size=500)
    return X, labels


def test_cluster_medians_parity(data):
    X, labels = data
    got = np.asarray(compute_cluster_medians_jax(X, labels.astype(np.int32), 4))
    want = scoring_np.compute_cluster_medians(X, labels, 4)
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_cluster_medians_empty_cluster_nan(data):
    X, labels = data
    got = np.asarray(compute_cluster_medians_jax(X, labels.astype(np.int32), 6))
    assert np.isnan(got[4]).all() and np.isnan(got[5]).all()
    want = scoring_np.compute_cluster_medians(X, labels, 6)
    np.testing.assert_allclose(got[:4], want[:4], atol=1e-12)


@pytest.mark.parametrize("from_data", [False, True])
def test_classify_parity(data, from_data):
    X, labels = data
    cfg = ScoringConfig(compute_global_medians_from_data=from_data)
    wj, sj, mj = classify_jax(X, labels, 4, cfg)
    wn, sn, mn = scoring_np.classify(X, labels, 4, cfg)
    np.testing.assert_allclose(np.asarray(sj), sn, atol=1e-10)
    np.testing.assert_array_equal(np.asarray(wj), wn)
    np.testing.assert_allclose(np.asarray(mj), mn, atol=1e-12)


def test_hist_medians_close_to_exact():
    """Histogram medians within a bin width of exact, NaN for empty clusters."""
    rng = np.random.default_rng(3)
    X = rng.uniform(size=(40_000, 5))
    labels = rng.integers(0, 7, size=40_000).astype(np.int32)  # cluster 7 empty
    got = np.asarray(compute_cluster_medians_hist_jax(X, labels, 8, bins=2048))
    want = scoring_np.compute_cluster_medians(X, labels, 8)
    assert np.isnan(got[7]).all()
    np.testing.assert_allclose(got[:7], want[:7], atol=1.0 / 2048)


def test_hist_medians_constant_column_exact():
    rng = np.random.default_rng(4)
    X = rng.uniform(size=(1000, 3))
    X[:, 1] = 0.25  # constant column must come out exactly
    labels = rng.integers(0, 3, size=1000).astype(np.int32)
    got = np.asarray(compute_cluster_medians_hist_jax(X, labels, 3))
    assert (got[:, 1] == 0.25).all()


@pytest.mark.parametrize("from_data", [False, True])
def test_hist_classify_category_parity(from_data):
    """Categories from histogram medians must match the exact path on a
    realistic blob workload (SURVEY.md §7.4: parity on categories, not raw
    scores, at scale)."""
    rng = np.random.default_rng(7)
    k = 8
    centers = rng.uniform(size=(k, 5))
    lab = rng.integers(0, k, size=100_000)
    X = np.clip(centers[lab] + rng.normal(size=(100_000, 5)) * 0.05, 0, 1)
    labels = lab.astype(np.int32)

    exact = ScoringConfig(median_method="sort",
                          compute_global_medians_from_data=from_data)
    hist = ScoringConfig(median_method="hist",
                         compute_global_medians_from_data=from_data)
    we, se, me = classify_jax(X, labels, k, exact)
    wh, sh, mh = classify_jax(X, labels, k, hist)
    np.testing.assert_allclose(np.asarray(mh), np.asarray(me), atol=1e-3)
    np.testing.assert_array_equal(np.asarray(wh), np.asarray(we))


def test_auto_median_threshold_routes():
    """auto = sort below the threshold (bit-exact vs numpy)."""
    rng = np.random.default_rng(9)
    X = rng.uniform(size=(512, 5))
    labels = rng.integers(0, 4, size=512).astype(np.int32)
    cfg = ScoringConfig(median_method="auto",
                        compute_global_medians_from_data=True)
    wj, sj, mj = classify_jax(X, labels, 4, cfg)
    wn, sn, mn = scoring_np.classify(X, labels, 4, cfg)
    np.testing.assert_allclose(np.asarray(mj), mn, atol=1e-12)
    np.testing.assert_array_equal(np.asarray(wj), wn)


def test_all_zero_scores_tiebreak_archival():
    """Empty-evidence clusters must fall to Archival via the rf tie-break
    (reference: scoring.py:102-107, SURVEY.md §2.3)."""
    X = np.full((8, 5), 0.5)  # deltas all zero vs default 0.5 global medians
    labels = np.zeros(8, dtype=np.int64)
    cfg = ScoringConfig()
    # delta == 0: non-Moderate categories need sign match (sign(0)=0 != ±1) so
    # they score 0; Moderate scores w*(1-0)^2 = 2.5 > 0 -> Moderate wins here.
    wj, sj, _ = classify_jax(X, labels, 1, cfg)
    assert cfg.categories[int(np.asarray(wj)[0])] == "Moderate"
    # A fully empty cluster (NaN medians) scores 0 everywhere -> Archival.
    wj2, sj2, _ = classify_jax(X, labels, 2, cfg)
    assert cfg.categories[int(np.asarray(wj2)[1])] == "Archival"
    assert np.allclose(np.asarray(sj2)[1], 0.0)


# ---------------------------------------------------------------------------
# Sharded scoring (VERDICT r2 #5): data-sharded histogram medians
# ---------------------------------------------------------------------------


def _blob_workload(n, k, seed=11):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(0.1, 0.9, size=(k, 5))
    lab = rng.integers(0, k, size=n)
    X = np.clip(centers[lab] + rng.normal(size=(n, 5)) * 0.05, 0, 1)
    return X.astype(np.float64), lab.astype(np.int32)


@pytest.mark.parametrize("mesh_shape", [
    {"data": 8},
    {"data": 4, "model": 2},   # 2D mesh: medians reduce over data only
])
def test_sharded_hist_medians_match_single_device(mesh_shape):
    X, labels, k = *_blob_workload(4096, 6), 6
    cfg = ScoringConfig(median_method="hist",
                        compute_global_medians_from_data=True)
    w1, s1, m1 = classify_jax(X, labels, k, cfg)
    w8, s8, m8 = classify_jax(X, labels, k, cfg, mesh_shape=mesh_shape)
    np.testing.assert_allclose(np.asarray(m8), np.asarray(m1),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s8), np.asarray(s1),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(w8), np.asarray(w1))


def test_sharded_scoring_category_parity_vs_exact():
    """Categories from the sharded hist path match the exact sort path."""
    X, labels, k = *_blob_workload(8192, 5, seed=13), 5
    cfg_exact = ScoringConfig(median_method="sort",
                              compute_global_medians_from_data=True)
    cfg_auto = ScoringConfig(compute_global_medians_from_data=True)
    we, _, _ = classify_jax(X, labels, k, cfg_exact)
    ws, _, _ = classify_jax(X, labels, k, cfg_auto, mesh_shape={"data": 8})
    np.testing.assert_array_equal(np.asarray(ws), np.asarray(we))


def test_sharded_scoring_pads_uneven_rows():
    """n not divisible by the mesh: sentinel-padded rows change nothing."""
    X, labels, k = *_blob_workload(1000, 3, seed=17), 3   # 1000 % 8 != 0
    cfg = ScoringConfig(median_method="hist",
                        compute_global_medians_from_data=True)
    w1, _, m1 = classify_jax(X, labels, k, cfg)
    w8, _, m8 = classify_jax(X, labels, k, cfg, mesh_shape={"data": 8})
    np.testing.assert_allclose(np.asarray(m8), np.asarray(m1),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(w8), np.asarray(w1))


def test_sharded_scoring_rejects_sort():
    X, labels = _blob_workload(256, 2)
    with pytest.raises(ValueError, match="single-device"):
        classify_jax(X, labels, 2,
                     ScoringConfig(median_method="sort"),
                     mesh_shape={"data": 8})


def test_model_score_honors_mesh_shape():
    """ReplicationPolicyModel.score routes through the sharded median stage
    and matches the unsharded model's categories (VERDICT r2 weak #5)."""
    from cdrs_tpu.models.replication import ReplicationPolicyModel
    from cdrs_tpu.config import KMeansConfig

    X, _ = _blob_workload(2048, 4, seed=23)
    kcfg = KMeansConfig(k=4, seed=3, max_iter=10)
    scfg = ScoringConfig(median_method="hist",
                         compute_global_medians_from_data=True)
    m1 = ReplicationPolicyModel(kcfg, scfg, backend="jax")
    m8 = ReplicationPolicyModel(kcfg, scfg, backend="jax",
                                mesh_shape={"data": 8})
    # Cluster once; the mesh under test is the SCORING stage (the sharded
    # kmeans threads a different per-shard PRNG stream by design, so labels
    # across meshes are not comparable).
    d1 = m1.run(np.asarray(X, np.float32))
    w8, s8, m8_med = m8.score(np.asarray(X, np.float32), d1.labels)
    np.testing.assert_allclose(m8_med, d1.cluster_medians,
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(w8, d1.category_idx)
