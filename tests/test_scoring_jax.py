"""Parity tests: JAX scoring backend vs the NumPy golden model."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import ScoringConfig
from cdrs_tpu.ops import scoring_np
from cdrs_tpu.ops.scoring_jax import (
    classify_jax,
    compute_cluster_medians_jax,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    X = rng.uniform(size=(500, 5))
    labels = rng.integers(0, 4, size=500)
    return X, labels


def test_cluster_medians_parity(data):
    X, labels = data
    got = np.asarray(compute_cluster_medians_jax(X, labels.astype(np.int32), 4))
    want = scoring_np.compute_cluster_medians(X, labels, 4)
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_cluster_medians_empty_cluster_nan(data):
    X, labels = data
    got = np.asarray(compute_cluster_medians_jax(X, labels.astype(np.int32), 6))
    assert np.isnan(got[4]).all() and np.isnan(got[5]).all()
    want = scoring_np.compute_cluster_medians(X, labels, 6)
    np.testing.assert_allclose(got[:4], want[:4], atol=1e-12)


@pytest.mark.parametrize("from_data", [False, True])
def test_classify_parity(data, from_data):
    X, labels = data
    cfg = ScoringConfig(compute_global_medians_from_data=from_data)
    wj, sj, mj = classify_jax(X, labels, 4, cfg)
    wn, sn, mn = scoring_np.classify(X, labels, 4, cfg)
    np.testing.assert_allclose(np.asarray(sj), sn, atol=1e-10)
    np.testing.assert_array_equal(np.asarray(wj), wn)
    np.testing.assert_allclose(np.asarray(mj), mn, atol=1e-12)


def test_all_zero_scores_tiebreak_archival():
    """Empty-evidence clusters must fall to Archival via the rf tie-break
    (reference: scoring.py:102-107, SURVEY.md §2.3)."""
    X = np.full((8, 5), 0.5)  # deltas all zero vs default 0.5 global medians
    labels = np.zeros(8, dtype=np.int64)
    cfg = ScoringConfig()
    # delta == 0: non-Moderate categories need sign match (sign(0)=0 != ±1) so
    # they score 0; Moderate scores w*(1-0)^2 = 2.5 > 0 -> Moderate wins here.
    wj, sj, _ = classify_jax(X, labels, 1, cfg)
    assert cfg.categories[int(np.asarray(wj)[0])] == "Moderate"
    # A fully empty cluster (NaN medians) scores 0 everywhere -> Archival.
    wj2, sj2, _ = classify_jax(X, labels, 2, cfg)
    assert cfg.categories[int(np.asarray(wj2)[1])] == "Archival"
    assert np.allclose(np.asarray(sj2)[1], 0.0)
