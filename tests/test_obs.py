"""Unified telemetry layer (cdrs_tpu/obs): spans, sink, counters,
recompile detection, kmeans convergence traces, and the cdrs metrics CLI."""

import json
import re
import threading

import numpy as np
import pytest

from cdrs_tpu.obs import JsonlSink, Telemetry, current, read_events, \
    run_metadata
from cdrs_tpu.obs.metrics_cli import main as metrics_main, prometheus_lines


# -- sink --------------------------------------------------------------------

def test_sink_one_line_per_event_and_append(tmp_path):
    p = str(tmp_path / "t.jsonl")
    with JsonlSink(p) as s:
        s.emit({"kind": "counter", "name": "a", "value": 1})
    with JsonlSink(p) as s:  # append-only across re-opens (kill/resume)
        s.emit({"kind": "counter", "name": "a", "value": 2})
    events = read_events(p)
    assert [e["value"] for e in events] == [1, 2]


def test_sink_thread_safety(tmp_path):
    p = str(tmp_path / "t.jsonl")
    sink = JsonlSink(p)

    def work(tid):
        for i in range(200):
            sink.emit({"tid": tid, "i": i})

    threads = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    events = read_events(p)
    assert len(events) == 800  # no torn/interleaved lines
    for tid in range(4):
        assert [e["i"] for e in events if e["tid"] == tid] == list(range(200))


def test_read_events_skips_torn_tail(tmp_path):
    p = tmp_path / "t.jsonl"
    p.write_text('{"kind": "gauge", "name": "x", "value": 1.0}\n{"kind": "ga')
    events = read_events(str(p))
    assert len(events) == 1 and events[0]["value"] == 1.0


def test_sink_serializes_numpy_scalars(tmp_path):
    p = str(tmp_path / "t.jsonl")
    with JsonlSink(p) as s:
        s.emit({"v": np.float32(1.5), "a": np.arange(3)})
    e = read_events(p)[0]
    assert e["v"] == 1.5 and e["a"] == [0, 1, 2]


# -- telemetry core ----------------------------------------------------------

def test_span_nesting_and_tree_fields(tmp_path):
    p = str(tmp_path / "t.jsonl")
    with Telemetry(JsonlSink(p), meta=False) as tel:
        with tel.span("outer"):
            with tel.span("inner"):
                pass
            with tel.span("inner"):
                pass
    spans = [e for e in read_events(p) if e["kind"] == "span"]
    # children emit before the parent (exit order)
    assert [s["name"] for s in spans] == ["inner", "inner", "outer"]
    outer = spans[-1]
    assert all(s["parent"] == outer["id"] for s in spans[:2])
    assert outer["parent"] is None
    assert all(s["dur"] >= 0 for s in spans)


def test_ambient_activation_and_counters():
    assert current() is None
    with Telemetry() as tel:  # sink-less: in-memory aggregates only
        assert current() is tel
        tel.counter_inc("c", 2)
        tel.counter_inc("c", 3)
        tel.gauge("g", 7.0)
        tel.histogram("h", 1.0)
        tel.histogram("h", 9.0)
        assert tel.counters["c"] == 5
        assert tel.gauges["g"] == 7.0
        assert tel.histograms["h"] == [1.0, 9.0]
    assert current() is None


def test_spans_are_per_thread():
    with Telemetry() as tel:
        parents = {}

        def work():
            with tel.span("worker") as s:
                parents["worker"] = s.parent

        with tel.span("main"):
            t = threading.Thread(target=work)
            t.start()
            t.join()
        # The worker thread's span must NOT claim the main thread's span
        # as a parent — each thread owns its stack.
        assert parents["worker"] is None


def test_run_metadata_basics():
    meta = run_metadata()
    assert meta["python"] and "numpy" in meta


# -- numpy kmeans convergence trace ------------------------------------------

def test_kmeans_np_emits_convergence_trace(tmp_path):
    from cdrs_tpu.ops.kmeans_np import kmeans

    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 0.05, (60, 3)),
                        rng.normal(1, 0.05, (60, 3))])
    p = str(tmp_path / "t.jsonl")
    with Telemetry(JsonlSink(p), meta=False):
        kmeans(X, 2, random_state=0)
    iters = [e for e in read_events(p) if e["kind"] == "kmeans_iter"]
    assert iters and iters[0]["backend"] == "numpy"
    assert [e["step"] for e in iters] == list(range(len(iters)))
    # Lloyd monotonicity: inertia never increases step to step.
    inertias = [e["inertia"] for e in iters]
    assert all(b <= a + 1e-9 for a, b in zip(inertias, inertias[1:]))
    # final shift below the default tol (the loop's exit condition)
    assert iters[-1]["shift"] < 1e-4 or len(iters) == 100


def test_kmeans_trace_off_emits_nothing(tmp_path):
    from cdrs_tpu.ops.kmeans_np import kmeans

    X = np.random.default_rng(1).normal(size=(40, 3))
    p = str(tmp_path / "t.jsonl")
    with Telemetry(JsonlSink(p), meta=False, kmeans_trace=False):
        kmeans(X, 2, random_state=0)
    assert not [e for e in read_events(p) if e["kind"] == "kmeans_iter"]


# -- jax: recompile counter + traced kernel ----------------------------------

def test_recompile_counter_same_shape_zero_new_shape_increments():
    pytest.importorskip("jax")
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(2)
    # Deliberately odd shapes so no other test already compiled them.
    X1 = rng.normal(size=(157, 6)).astype(np.float32)
    X2 = rng.normal(size=(211, 6)).astype(np.float32)
    with Telemetry() as tel:
        kmeans_jax_full(X1, 3, seed=0, max_iter=4)
        calls_1 = tel.counters["jit.calls.kmeans_jax_full"]
        recompiles_1 = tel.counters["jit.recompiles.kmeans_jax_full"]
        # Repeated same-shape call: calls tick, recompiles must NOT.
        kmeans_jax_full(X1, 3, seed=0, max_iter=4)
        assert tel.counters["jit.calls.kmeans_jax_full"] == calls_1 + 1
        assert tel.counters["jit.recompiles.kmeans_jax_full"] == recompiles_1
        # Shape change: a fresh abstract signature must compile.
        kmeans_jax_full(X2, 3, seed=0, max_iter=4)
        assert tel.counters["jit.recompiles.kmeans_jax_full"] \
            >= recompiles_1 + 1
    # Warm-before-telemetry: the same shapes under a FRESH instrument hit
    # the compilation cache, so no recompile may be reported (the verdict
    # comes from the cache-miss delta, not first-seen-by-this-instrument).
    with Telemetry() as tel2:
        kmeans_jax_full(X1, 3, seed=0, max_iter=4)
        assert tel2.counters["jit.calls.kmeans_jax_full"] == 1
        assert "jit.recompiles.kmeans_jax_full" not in tel2.counters


def test_kmeans_jax_traced_matches_untraced():
    """The traced program is a diagnostic view, not a different algorithm:
    centroids/labels/iteration count must match the untraced run, and the
    trace must agree with the returned scalars."""
    pytest.importorskip("jax")
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(3)
    X = np.concatenate([rng.normal(0, 0.05, (90, 4)),
                        rng.normal(1, 0.05, (90, 4))]).astype(np.float64)
    c_ref, l_ref, it_ref, shift_ref = kmeans_jax_full(X, 2, seed=0,
                                                      max_iter=20)
    events = []
    with Telemetry() as tel:
        tel._emit = events.append  # capture without a sink
        c, labels, it, shift = kmeans_jax_full(X, 2, seed=0, max_iter=20)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c_ref), atol=1e-12)
    np.testing.assert_array_equal(np.asarray(labels), np.asarray(l_ref))
    assert it == it_ref
    iters = [e for e in events if e["kind"] == "kmeans_iter"]
    assert len(iters) == it
    assert iters[-1]["shift"] == pytest.approx(shift, rel=1e-5)
    inertias = [e["inertia"] for e in iters]
    assert all(b <= a + 1e-6 for a, b in zip(inertias, inertias[1:]))


def test_kmeans_jax_traced_sharded_matches_single_device():
    pytest.importorskip("jax")
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full
    from cdrs_tpu.ops.kmeans_np import kmeans_plusplus_init

    rng = np.random.default_rng(4)
    X = rng.normal(size=(160, 4)).astype(np.float64)
    # Identical starting centroids: the on-device init's PRNG stream is
    # shard-dependent by design (same contract as the parity tests).
    init = kmeans_plusplus_init(X, 3, random_state=0)

    def trace_with(mesh):
        events = []
        with Telemetry() as tel:
            tel._emit = events.append
            kmeans_jax_full(X, 3, seed=0, max_iter=8, mesh_shape=mesh,
                            init_centroids=init)
        return [(e["inertia"], e["shift"]) for e in events
                if e["kind"] == "kmeans_iter"]

    single = trace_with(None)
    sharded = trace_with({"data": 4})
    assert len(single) == len(sharded) > 0
    for (i1, s1), (i2, s2) in zip(single, sharded):
        assert i1 == pytest.approx(i2, rel=1e-6)
        assert s1 == pytest.approx(s2, rel=1e-5, abs=1e-10)


# -- controller integration --------------------------------------------------

@pytest.fixture(scope="module")
def small_workload():
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=120, seed=11))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=480.0, seed=12))
    return manifest, events


def test_controller_telemetry_counters_and_histograms(tmp_path,
                                                      small_workload):
    from cdrs_tpu.config import KMeansConfig, validated_scoring_config
    from cdrs_tpu.control import ControllerConfig, ReplicationController

    manifest, events = small_workload
    cfg = ControllerConfig(window_seconds=120.0, max_files_per_window=15,
                           hysteresis_windows=1,
                           kmeans=KMeansConfig(k=6, seed=42),
                           scoring=validated_scoring_config())
    mp = str(tmp_path / "m.jsonl")
    with Telemetry(JsonlSink(mp), meta=False) as tel:
        res = ReplicationController(manifest, cfg).run(events,
                                                       metrics_path=mp)
    assert tel.counters["controller.windows"] == len(res.records)
    assert tel.counters["migrate.files_moved"] == sum(
        r["moves_applied"] for r in res.records)
    # Cold-start plan over 120 files at a 15-file cap: the backlog must
    # have deferred nothing by hysteresis but plenty by the cap... the cap
    # breaks the loop, so hysteresis deferrals specifically are counted
    # when frozen files are *passed over*, which this workload produces
    # after its first re-plan windows.
    assert "controller.fold.seconds" in tel.histograms
    assert len(tel.histograms["controller.total.seconds"]) \
        == len(res.records)
    events_stream = read_events(mp)
    windows = [e for e in events_stream if e.get("kind") == "window"]
    assert len(windows) == len(res.records)
    assert [w["window"] for w in windows] == \
        [r["window"] for r in res.records]
    # counters interleave in the same stream and are still parseable
    assert any(e.get("kind") == "counter" for e in events_stream)


def test_scheduler_deferral_counts():
    from cdrs_tpu.control import MigrationScheduler, PlanMove

    s = MigrationScheduler(6, max_bytes_per_window=150,
                           hysteresis_windows=3)
    moves = [PlanMove(i, 1, 3, 2, 0, bytes_moved=100, priority=float(6 - i))
             for i in range(6)]
    s.submit(moves)
    first = s.schedule(0)
    assert [m.file_index for m in first] == [0]
    assert s.last_deferred_hysteresis == 0
    assert s.last_deferred_budget == 5
    s.submit(moves)  # files 0 frozen for 3 windows
    s.schedule(1)
    assert s.last_deferred_hysteresis == 1  # file 0 passed over, frozen


# -- cdrs metrics CLI / acceptance -------------------------------------------

def test_cli_run_metrics_then_summarize(tmp_path, capsys):
    """Acceptance: cdrs run --metrics out.jsonl; cdrs metrics summarize
    shows a span tree covering every pipeline stage, per-iteration kmeans
    convergence records, and the recompile counter."""
    pytest.importorskip("jax")
    from cdrs_tpu.cli import main

    mp = str(tmp_path / "out.jsonl")
    rc = main(["run", "--n", "80", "--duration_seconds", "30", "--k", "4",
               "--seed", "1", "--backend", "jax", "--evaluate",
               "--outdir", str(tmp_path / "out"), "--metrics", mp])
    assert rc == 0
    capsys.readouterr()
    assert main(["metrics", "summarize", mp]) == 0
    text = capsys.readouterr().out
    for stage in ("pipeline", "gen", "simulate", "features", "cluster",
                  "evaluate", "io"):
        assert stage in text, f"stage {stage} missing from summarize"
    assert "jit.recompiles.kmeans_jax_full" in text
    assert "KMeans convergence traces" in text
    assert "iterations" in text

    # tail + prometheus export round out the CLI surface
    assert main(["metrics", "tail", mp, "-n", "5"]) == 0
    capsys.readouterr()
    out_prom = str(tmp_path / "metrics.prom")
    assert main(["metrics", "export", mp, "--format", "prometheus",
                 "--out", out_prom]) == 0
    prom = open(out_prom).read()
    assert "# TYPE cdrs_jit_recompiles_kmeans_jax_full counter" in prom
    assert "cdrs_kmeans_iterations_count" in prom


def test_cli_run_metrics_numpy_backend(tmp_path, capsys):
    """The numpy backend traces too (kmeans_np) — no jax required."""
    from cdrs_tpu.cli import main

    mp = str(tmp_path / "out.jsonl")
    rc = main(["pipeline", "--n", "60", "--duration_seconds", "30",
               "--k", "4", "--seed", "2", "--backend", "numpy",
               "--outdir", str(tmp_path / "out"), "--metrics", mp])
    assert rc == 0
    events = read_events(mp)
    assert [e for e in events if e.get("kind") == "kmeans_iter"
            and e.get("backend") == "numpy"]
    span_names = {e["name"] for e in events if e.get("kind") == "span"}
    assert {"pipeline", "gen", "simulate", "features",
            "cluster"} <= span_names


def test_metrics_summarize_missing_file(capsys, tmp_path):
    from cdrs_tpu.cli import main

    assert main(["metrics", "summarize",
                 str(tmp_path / "nope.jsonl")]) == 1


def test_prometheus_lines_shapes():
    events = [
        {"kind": "counter", "name": "a.b", "value": 3.0},
        {"kind": "gauge", "name": "g", "value": 1.5},
        {"kind": "hist", "name": "h", "value": 1.0},
        {"kind": "hist", "name": "h", "value": 3.0},
    ]
    lines = prometheus_lines(events)
    assert "cdrs_a_b 3" in lines
    assert "# TYPE cdrs_g gauge" in lines
    assert "cdrs_h_count 2" in lines
    assert any(ln.startswith('cdrs_h{quantile="0.95"}') for ln in lines)


def test_summarize_aggregates_appended_runs(tmp_path, capsys):
    """Two runs appending to one stream: span ids restart per process, so
    the reader must scope them by the run stamp — the first run's spans
    aggregate (x2) instead of being shadowed, and counters sum."""
    p = str(tmp_path / "t.jsonl")
    for _ in range(2):
        with Telemetry(JsonlSink(p), meta=False) as tel:
            with tel.span("root"):
                with tel.span("child"):
                    pass
            tel.counter_inc("c", 3)
    assert metrics_main(["summarize", p]) == 0
    out = capsys.readouterr().out
    assert "x2" in out           # both runs' root spans counted
    assert re.search(r"\bc\s+6\b", out)  # 3 + 3, not last-wins 3
    lines = prometheus_lines(read_events(p))
    assert "cdrs_c 6" in lines


def test_metrics_cli_tail_window_records(tmp_path, capsys):
    """summarize/tail digest a controller window stream (the cdrs control
    --metrics output) — not only full telemetry streams."""
    p = tmp_path / "w.jsonl"
    recs = [{"kind": "window", "window": i, "n_events": 10 * i,
             "recluster": i == 0, "recluster_mode": "full" if i == 0
             else None, "moves_applied": i, "bytes_migrated": 100 * i}
            for i in range(3)]
    # Repeat window 2 (the kill/resume tail contract): the digest must
    # take the LAST record per window index, not double-count.
    recs.append({**recs[2], "n_events": 20, "bytes_migrated": 999})
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert metrics_main(["summarize", str(p)]) == 0
    out = capsys.readouterr().out
    assert "Controller windows: 3" in out and "1 reclusters" in out
    assert "30 events" in out   # 0 + 10 + 20: window 2 counted once
    assert "50 events" not in out  # ...not twice (the crashed-tail repeat)
    # tail -n 0 prints nothing (not the whole stream)
    assert metrics_main(["tail", str(p), "-n", "0"]) == 0
    assert capsys.readouterr().out == ""
