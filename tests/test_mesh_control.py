"""Mesh-sharded control loop: decision identity vs the single-device oracle.

PR 11 wires ``ControllerConfig.mesh_shape`` through the whole per-window
device computation (cluster step, scoring medians, feature fold, drift).
The single-device path stays the equivalence oracle — the PR-8 compat
pattern: on the same seed a ``{"data": 8}`` run must make IDENTICAL
decisions (assignments, category populations, plan hashes, migrations)
while the drift scalars agree to fp tolerance (float psum association),
and a checkpoint must be portable across mesh shapes (a runtime choice,
not checkpoint state).

``CDRS_CHAOS_SEED`` varies the workload seeds — CI's mesh smoke step
sweeps it over 0/1/2 under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (single process;
the multiprocess-collective limitation that keeps
test_distributed_smoke.py skipped does not apply).
"""

import dataclasses
import os

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.sim.access import simulate_access_with_shift
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))

#: Drift scalars agree across mesh shapes only to float-psum tolerance.
_DRIFT_FIELDS = ("drift", "centroid_shift", "population_delta")


@pytest.fixture(scope="module")
def scenario():
    # 403 files: NOT divisible by 8, so every shard boundary exercises the
    # pad_rows/prefix_mask contract.
    manifest = generate_population(
        GeneratorConfig(n_files=403, seed=7 + SEED))
    events, _ = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=1200.0, seed=8 + SEED),
        600.0, {"hot": "archival", "archival": "hot"})
    # Histogram medians on BOTH sides: integer count statistics, bitwise
    # identical at any mesh shape ("auto" would resolve to the exact sort
    # single-device and hist sharded — different estimates per shape).
    scoring = dataclasses.replace(validated_scoring_config(),
                                  median_method="hist")
    return manifest, events, scoring


def _run(scenario, mesh, checkpoint_path=None, max_windows=None):
    manifest, events, scoring = scenario
    cfg = ControllerConfig(
        window_seconds=100.0, drift_threshold=0.02, backend="jax",
        kmeans=KMeansConfig(k=12, seed=42), scoring=scoring,
        mesh_shape=mesh, default_rf=2)
    ctl = ReplicationController(manifest, cfg)
    return ctl.run(events, checkpoint_path=checkpoint_path,
                   max_windows=max_windows)


def _strip(records):
    drop = ("seconds", "mesh") + _DRIFT_FIELDS
    return [{k: v for k, v in r.items() if k not in drop}
            for r in records]


def test_mesh_run_decision_identical_to_single_device(scenario):
    r1 = _run(scenario, None)
    r8 = _run(scenario, {"data": 8})
    assert _strip(r1.records) == _strip(r8.records)
    assert np.array_equal(r1.rf, r8.rf)
    assert np.array_equal(r1.category_idx, r8.category_idx)
    # Same re-cluster decisions, same plan hash trail.
    assert [r["plan_hash"] for r in r1.records] \
        == [r["plan_hash"] for r in r8.records]
    for a, b in zip(r1.records, r8.records):
        for f in _DRIFT_FIELDS:
            if a.get(f) is None:
                assert b.get(f) is None
            else:
                assert b[f] == pytest.approx(a[f], abs=1e-5)
    # The mesh stamp rides every mesh-run record and no oracle record.
    assert all(r["mesh"]["devices"] == 8 for r in r8.records)
    assert all("mesh" not in r for r in r1.records)


def test_cold_init_identical_across_mesh_shapes(scenario):
    """The D²/kmeans|| init noise is keyed to the global row, so a COLD
    re-cluster draws identical centroids at any data=N (the piece that
    makes controller decision-identity possible at all)."""
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(11 + SEED)
    X = rng.random((403, 5)).astype(np.float32)
    for init in ("d2", "kmeans||"):
        ref = kmeans_jax_full(X, 8, seed=SEED, max_iter=0, tol=0.0,
                              init_method=init)
        for ndev in (2, 8):
            got = kmeans_jax_full(X, 8, seed=SEED, max_iter=0, tol=0.0,
                                  init_method=init,
                                  mesh_shape={"data": ndev})
            np.testing.assert_array_equal(np.asarray(got[0]),
                                          np.asarray(ref[0]), err_msg=init)


@pytest.mark.parametrize("from_mesh,to_mesh",
                         [(None, {"data": 8}), ({"data": 8}, None)])
def test_checkpoint_portable_across_mesh_shapes(scenario, tmp_path,
                                                from_mesh, to_mesh):
    """Mesh shape is a runtime choice, not checkpoint state: a snapshot
    written at one shape resumes at another with identical decisions
    (records match the resuming shape's uninterrupted run exactly on
    every decision field; drift scalars to fp tolerance)."""
    full = _run(scenario, to_mesh)
    ck = str(tmp_path / f"mesh_{from_mesh is None}.npz")
    a = _run(scenario, from_mesh, checkpoint_path=ck, max_windows=6)
    b = _run(scenario, to_mesh, checkpoint_path=ck)
    stitched = _strip(a.records) + _strip(b.records)
    assert stitched == _strip(full.records)
    assert np.array_equal(b.rf, full.rf)
    assert np.array_equal(b.category_idx, full.category_idx)
    # Drift scalars of the resumed half agree with the uninterrupted
    # run to fp tolerance only: the checkpoint carries the OTHER shape's
    # accepted centroids, which differ in ULPs (float psum association).
    tail = full.records[len(a.records):]
    for got, want in zip(b.records, tail):
        for f in _DRIFT_FIELDS:
            if want.get(f) is None:
                assert got.get(f) is None
            else:
                assert got[f] == pytest.approx(want[f], abs=1e-5)


def test_model_assignments_and_populations_identical(scenario):
    """Model-level oracle check: cluster + score at data=8 produces the
    same labels and per-category populations as single-device."""
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    manifest, events, scoring = scenario
    rng = np.random.default_rng(5 + SEED)
    X = rng.random((403, 5)).astype(np.float32)
    km = KMeansConfig(k=12, seed=42)
    d1 = ReplicationPolicyModel(km, scoring, backend="jax").run(X)
    d8 = ReplicationPolicyModel(km, scoring, backend="jax",
                                mesh_shape={"data": 8}).run(X)
    np.testing.assert_array_equal(d1.labels, d8.labels)
    np.testing.assert_array_equal(d1.category_idx, d8.category_idx)
    np.testing.assert_array_equal(
        np.bincount(d1.category_idx[d1.labels], minlength=4),
        np.bincount(d8.category_idx[d8.labels], minlength=4))


def test_mesh_requires_jax_backend(scenario):
    manifest, events, scoring = scenario
    with pytest.raises(ValueError, match="backend='jax'"):
        ControllerConfig(backend="numpy", mesh_shape={"data": 8})


def test_mesh_shape_validated_at_config(scenario):
    with pytest.raises(ValueError, match="unknown mesh axis"):
        ControllerConfig(backend="jax", mesh_shape={"rows": 8})


def test_mesh_records_carry_collective_estimate(scenario):
    """The windows/sec-vs-mesh-size observable: every record carries the
    device count and the (k, d+1) psum traffic estimate."""
    from cdrs_tpu.parallel.mesh import collective_bytes_estimate

    r = _run(scenario, {"data": 4})
    want = collective_bytes_estimate(12 * 6 * 4, 4)
    for rec in r.records:
        assert rec["mesh"] == {"devices": 4,
                               "collective_bytes_per_iter": want}


def test_pacing_digest_surfaces_devices(scenario):
    from cdrs_tpu.obs.aggregate import pacing_digest

    r = _run(scenario, {"data": 8})
    pacing = pacing_digest(r.records)
    assert pacing["devices"] == 8
    assert pacing["collective_bytes_per_iter"] > 0
    # Mesh-less streams render unchanged.
    r1 = _run(scenario, None)
    assert "devices" not in pacing_digest(r1.records)
