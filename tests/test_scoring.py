"""Unit tests for the scoring backend (ops/scoring_np.py, compat API).

The reference's only executable verification of the scoring math is a demo
that runs at import time (src/scoring.py:133-175, SURVEY.md §4.1) — here it
becomes a real test with the oracle output captured from running the
reference: C1→Hot, C2→Archival, C3→Archival, C4→Hot.
"""

import numpy as np
import pytest

from cdrs_tpu.compat.reference_api import ClusterClassifier
from cdrs_tpu.config import CATEGORIES, ScoringConfig
from cdrs_tpu.ops.scoring_np import (
    classify,
    classify_medians,
    compute_cluster_medians,
    score_table,
)

# ---------------------------------------------------------------------------
# The reference's inline example (src/scoring.py:137-165) as a fixture.
# ---------------------------------------------------------------------------

INLINE_CLUSTERS = {
    "C1": {"IOPS": [100, 110, 105], "Latency": [2, 3, 2.5]},
    "C2": {"IOPS": [50, 55, 60], "Latency": [5, 6, 5.5]},
    "C3": {"IOPS": [10, 12, 11], "Latency": [8, 9, 7]},
    "C4": {"IOPS": [200, 210, 220], "Latency": [1, 1.5, 1.2]},
}
INLINE_GLOBAL_MEDIANS = {"IOPS": 60, "Latency": 4}
INLINE_WEIGHTS = {
    "Hot": {"IOPS": 1.0, "Latency": 0.8},
    "Shared": {"IOPS": 0.7, "Latency": 0.7},
    "Moderate": {"IOPS": 0.5, "Latency": 0.5},
    "Archival": {"IOPS": 0.9, "Latency": 1.0},
}
INLINE_DIRECTIONS = {
    "Hot": {"IOPS": +1, "Latency": -1},
    "Shared": {"IOPS": +1, "Latency": +1},
    "Moderate": {"IOPS": 0, "Latency": 0},
    "Archival": {"IOPS": -1, "Latency": +1},
}
INLINE_RF = {"Hot": 3, "Shared": 2, "Moderate": 1, "Archival": 4}


def test_reference_inline_example():
    clf = ClusterClassifier(INLINE_GLOBAL_MEDIANS, INLINE_WEIGHTS,
                            INLINE_DIRECTIONS, INLINE_RF)
    results = clf.classify(INLINE_CLUSTERS)
    assert results == {"C1": "Hot", "C2": "Archival", "C3": "Archival", "C4": "Hot"}


def test_inline_example_scores_hand_computed():
    # C1 medians: IOPS 105, Latency 2.5 -> delta (45, -1.5)
    # Hot: 1.0*45^2 + 0.8*1.5^2 = 2026.8 ; Shared: 0.7*45^2 = 1417.5
    cfg = ScoringConfig(
        features=("IOPS", "Latency"),
        global_medians=INLINE_GLOBAL_MEDIANS,
        weights=INLINE_WEIGHTS,
        directions=INLINE_DIRECTIONS,
        replication_factors=INLINE_RF,
    )
    scores = score_table(np.array([[105.0, 2.5]]), cfg)
    np.testing.assert_allclose(scores[0], [2026.8, 1417.5, 0.0, 0.0])


def test_vectorized_matches_compat_api():
    cfg = ScoringConfig(
        features=("IOPS", "Latency"),
        global_medians=INLINE_GLOBAL_MEDIANS,
        weights=INLINE_WEIGHTS,
        directions=INLINE_DIRECTIONS,
        replication_factors=INLINE_RF,
    )
    medians = np.array([
        [105.0, 2.5], [55.0, 5.5], [11.0, 8.0], [210.0, 1.2],
    ])
    winner, scores = classify_medians(medians, cfg)
    assert [cfg.categories[int(w)] for w in winner] == \
        ["Hot", "Archival", "Archival", "Hot"]

    clf = ClusterClassifier(INLINE_GLOBAL_MEDIANS, INLINE_WEIGHTS,
                            INLINE_DIRECTIONS, INLINE_RF)
    for row, w in zip(medians, winner):
        cm = {"IOPS": row[0], "Latency": row[1]}
        for cat in cfg.categories:
            expected = clf.score_category(cm, cat)
            got = scores[list(medians.tolist()).index(row.tolist()),
                         cfg.categories.index(cat)]
            np.testing.assert_allclose(got, expected)


def test_all_zero_scores_tie_break_to_archival():
    # delta exactly 0 everywhere: non-Moderate categories score only where
    # dir == 0 (np.sign(0) == 0, scoring.py:81); Moderate scores w*(1-0)^2.
    # With the production config Moderate has all dirs 0 but is handled by the
    # Moderate branch; others have nonzero dirs -> 0.  Moderate wins outright.
    cfg = ScoringConfig()
    medians = np.full((1, 5), 0.5)  # equals placeholder global medians
    winner, scores = classify_medians(medians, cfg)
    assert CATEGORIES[int(winner[0])] == "Moderate"

    # NaN medians (empty cluster) -> all scores 0 -> rf tie-break -> Archival
    # (rf 4 > 3 > 2 > 1; SURVEY.md §2.3).
    winner2, scores2 = classify_medians(np.full((1, 5), np.nan), cfg)
    assert np.all(scores2 == 0)
    assert CATEGORIES[int(winner2[0])] == "Archival"


def test_moderate_band_boundary():
    cfg = ScoringConfig(
        features=("f",),
        global_medians={"f": 0.5},
        weights={c: {"f": 1.0} for c in CATEGORIES},
        directions={"Hot": {"f": 1}, "Shared": {"f": 1},
                    "Moderate": {"f": 0}, "Archival": {"f": -1}},
        replication_factors={"Hot": 3, "Shared": 2, "Moderate": 1, "Archival": 4},
    )
    mod = list(CATEGORIES).index("Moderate")
    # binary-exact deltas: 0.0625 < 0.1 -> Moderate scores (1-0.0625)^2
    s = score_table(np.array([[0.5625]]), cfg)
    np.testing.assert_allclose(s[0, mod], (1 - 0.0625) ** 2, rtol=1e-12)
    # |delta| = 0.125 >= 0.1 -> outside the band: no Moderate score
    s = score_table(np.array([[0.625]]), cfg)
    assert s[0, mod] == 0.0


def test_direction_gating():
    cfg = ScoringConfig(
        features=("f",),
        global_medians={"f": 0.0},
        weights={c: {"f": 1.0} for c in CATEGORIES},
        directions={"Hot": {"f": 1}, "Shared": {"f": -1},
                    "Moderate": {"f": 0}, "Archival": {"f": 0}},
        replication_factors={"Hot": 3, "Shared": 2, "Moderate": 1, "Archival": 4},
    )
    s = score_table(np.array([[0.4]]), cfg)
    cats = list(CATEGORIES)
    assert s[0, cats.index("Hot")] > 0          # sign matches +1
    assert s[0, cats.index("Shared")] == 0.0    # sign mismatch
    # dir == 0 scores regardless of delta (scoring.py:81, SURVEY.md §6.1.9)
    np.testing.assert_allclose(s[0, cats.index("Archival")], 0.16)
    # delta == 0 scores only when dir == 0 (np.sign(0) == 0)
    s0 = score_table(np.array([[0.0]]), cfg)
    assert s0[0, cats.index("Hot")] == 0.0
    assert s0[0, cats.index("Archival")] == 0.0  # 1.0 * 0^2


def test_cluster_medians_and_full_classify():
    rng = np.random.default_rng(0)
    X = rng.random((40, 5))
    labels = np.repeat(np.arange(4), 10)
    medians = compute_cluster_medians(X, labels, 4)
    for j in range(4):
        np.testing.assert_allclose(medians[j], np.median(X[labels == j], axis=0))
    # empty cluster -> NaN row
    medians5 = compute_cluster_medians(X, labels, 5)
    assert np.all(np.isnan(medians5[4]))

    winner, scores, med = classify(X, labels, 4, ScoringConfig())
    assert winner.shape == (4,)
    assert scores.shape == (4, 4)
    np.testing.assert_allclose(med, medians)


def test_compute_global_medians_from_data():
    cfg = ScoringConfig(compute_global_medians_from_data=True)
    rng = np.random.default_rng(1)
    X = rng.random((100, 5))
    labels = np.zeros(100, dtype=np.int64)
    winner, scores, medians = classify(X, labels, 1, cfg)
    # one cluster whose medians equal the global medians -> all deltas 0
    # -> Moderate wins (its band rewards zero deviation).
    assert CATEGORIES[int(winner[0])] == "Moderate"


def test_numpy_hist_medians_match_jax(tmp_path):
    """Both backends honor median_method='hist' with matching bins/medians
    (ADVICE r2: numpy used to silently ignore it)."""
    pytest.importorskip("jax")
    from cdrs_tpu.ops.scoring_jax import classify_jax
    from cdrs_tpu.ops.scoring_np import classify

    rng = np.random.default_rng(41)
    X = rng.uniform(size=(50_000, 5))
    labels = rng.integers(0, 6, size=50_000).astype(np.int32)
    cfg = ScoringConfig(median_method="hist",
                        compute_global_medians_from_data=True)
    wn, sn, mn = classify(X, labels, 6, cfg)
    wj, sj, mj = classify_jax(X.astype(np.float32), labels, 6, cfg)
    np.testing.assert_allclose(mn, np.asarray(mj), atol=1e-3)
    np.testing.assert_array_equal(wn, np.asarray(wj))


def test_scoring_config_rejects_bad_median_method():
    from cdrs_tpu.config import scoring_config_from_dict

    with pytest.raises(ValueError, match="median_method"):
        scoring_config_from_dict({"median_method": "histo"})
    with pytest.raises(ValueError, match="median_bins"):
        scoring_config_from_dict({"median_bins": 1})


def test_numpy_classify_rejects_bad_median_method():
    from cdrs_tpu.ops.scoring_np import classify

    X = np.random.default_rng(0).uniform(size=(32, 5))
    labels = np.zeros(32, dtype=np.int32)
    cfg = ScoringConfig()
    cfg.median_method = "bogus"
    with pytest.raises(ValueError, match="median_method"):
        classify(X, labels, 1, cfg)
