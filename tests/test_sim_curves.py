"""Scenario-matrix sim generators (sim/access.py): diurnal curve,
phased drift patterns, flash-crowd burst — the property tests ISSUE 10
requires, swept across workload seeds via ``CDRS_CHAOS_SEED`` (CI runs
the scenario sweep itself; these pin the generators' contracts)."""

import os

import numpy as np
import pytest

from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.sim.access import (
    jittered_rates,
    simulate_access,
    simulate_access_phased,
    simulate_access_with_shift,
    simulate_diurnal,
    simulate_flash_crowd,
)
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
FLIP = {"hot": "archival", "archival": "hot"}


@pytest.fixture(scope="module")
def manifest():
    return generate_population(GeneratorConfig(n_files=250, seed=SEED))


def _cfg(duration=600.0, seed=SEED + 1):
    return SimulatorConfig(duration_seconds=duration, seed=seed)


def _eq(a, b) -> bool:
    return (np.array_equal(a.ts, b.ts) and np.array_equal(a.path_id,
                                                          b.path_id)
            and np.array_equal(a.op, b.op)
            and np.array_equal(a.client_id, b.client_id))


# -- diurnal -----------------------------------------------------------------

def test_diurnal_mass_conservation(manifest):
    """The curve only re-times events: per-file counts (and so the whole
    cumulative feature mass) equal the flat Poisson stream's bit-for-bit
    — same rng draws, different inverse-CDF placement."""
    flat = simulate_access(manifest, _cfg())
    diur = simulate_diurnal(manifest, _cfg(), amplitude=0.8)
    assert len(diur) == len(flat)
    assert np.array_equal(
        np.bincount(flat.path_id, minlength=len(manifest)),
        np.bincount(diur.path_id, minlength=len(manifest)))


def test_diurnal_zero_amplitude_is_flat(manifest):
    flat = simulate_access(manifest, _cfg())
    d0 = simulate_diurnal(manifest, _cfg(), amplitude=0.0)
    assert np.array_equal(d0.path_id, flat.path_id)
    assert np.array_equal(d0.op, flat.op)
    assert np.allclose(d0.ts, flat.ts)


def test_diurnal_shapes_time(manifest):
    """phase=0 over one period puts the sine's positive half first: the
    first half-window must carry measurably more than half the mass."""
    diur = simulate_diurnal(manifest, _cfg(), amplitude=0.8)
    t0 = float(np.ceil(manifest.creation_ts.max())) + 1.0
    frac_front = float((diur.ts < t0 + 300.0).mean())
    assert frac_front > 0.55
    assert np.all(np.diff(diur.ts) >= 0)  # globally time-sorted


def test_diurnal_validation(manifest):
    with pytest.raises(ValueError, match="amplitude"):
        simulate_diurnal(manifest, _cfg(), amplitude=1.0)
    with pytest.raises(ValueError, match="period"):
        simulate_diurnal(manifest, _cfg(), period=0.0)


# -- flash crowd -------------------------------------------------------------

def test_flash_crowd_burst_integral(manifest):
    """The burst's extra events match its rate integral: boost x the
    cohort's mean read rate x the burst span (Poisson mean), within the
    sampling noise of a few thousand draws."""
    cfg = _cfg(duration=600.0)
    cohort = np.asarray([c == "hot" for c in manifest.category])
    base = simulate_access(manifest, cfg)
    boost, dur = 50.0, 120.0
    ev, mask = simulate_flash_crowd(manifest, cfg, cohort=cohort,
                                    start=200.0, duration=dur, boost=boost)
    assert np.array_equal(mask, cohort)
    extra = len(ev) - len(base)
    read_mu = sum(cfg.rate_profiles[manifest.category[i]]["read_rate"]
                  for i in np.flatnonzero(cohort))
    expected = boost * read_mu * dur
    assert expected > 300  # enough mass for the tolerance to be fair
    assert abs(extra / expected - 1.0) < 0.2
    # burst events land inside the burst span only
    t0 = float(np.ceil(manifest.creation_ts.max())) + 1.0
    in_burst = (ev.ts >= t0 + 200.0) & (ev.ts < t0 + 200.0 + dur)
    base_in = ((base.ts >= t0 + 200.0) & (base.ts < t0 + 200.0 + dur)).sum()
    assert int(in_burst.sum()) - int(base_in) == extra


# -- drift patterns ----------------------------------------------------------

def test_phased_single_shift_is_with_shift(manifest):
    """simulate_access_with_shift delegates to the phased generator;
    the single-shift case must stay bit-identical to the historical
    two-phase output (the control_bench pinned artifact rides on it)."""
    ev1, fl1 = simulate_access_with_shift(manifest, _cfg(), shift_at=300.0,
                                          category_flip=FLIP)
    ev2, fl2 = simulate_access_phased(manifest, _cfg(),
                                      [(300.0, FLIP, None)])
    assert _eq(ev1, ev2)
    assert np.array_equal(fl1, fl2)


def test_drift_determinism_per_seed(manifest):
    """Same spec + seed => identical streams; different seed => not."""
    shifts = [(150.0, FLIP, None), (300.0, FLIP, None), (450.0, FLIP, None)]
    a, ca = simulate_access_phased(manifest, _cfg(), shifts)
    b, cb = simulate_access_phased(manifest, _cfg(), shifts)
    assert _eq(a, b) and np.array_equal(ca, cb)
    c, _ = simulate_access_phased(manifest, _cfg(seed=SEED + 99), shifts)
    assert not (len(a) == len(c) and np.array_equal(a.ts, c.ts))


def test_adversarial_even_cycles_revert(manifest):
    """An even number of self-inverse flips ends back at the planted
    categories — the workload really is back to normal, and the changed
    mask must say so."""
    ev, changed = simulate_access_phased(
        manifest, _cfg(), [(200.0, FLIP, None), (400.0, FLIP, None)])
    assert not changed.any()
    assert np.all(np.diff(ev.ts) >= 0)
    ev3, changed3 = simulate_access_phased(
        manifest, _cfg(),
        [(150.0, FLIP, None), (300.0, FLIP, None), (450.0, FLIP, None)])
    cohort = np.asarray([c in FLIP for c in manifest.category])
    assert np.array_equal(changed3, cohort)


def test_gradual_waves_are_cumulative(manifest):
    """Disjoint-cohort waves accumulate: the final changed mask is the
    union of the waves."""
    cohort = np.asarray([c in FLIP for c in manifest.category])
    ids = np.flatnonzero(cohort)
    w1 = np.zeros(len(manifest), dtype=bool)
    w1[ids[: len(ids) // 2]] = True
    w2 = np.zeros(len(manifest), dtype=bool)
    w2[ids[len(ids) // 2:]] = True
    _, changed = simulate_access_phased(
        manifest, _cfg(), [(200.0, FLIP, w1), (400.0, FLIP, w2)])
    assert np.array_equal(changed, w1 | w2)


def test_phased_validation(manifest):
    with pytest.raises(ValueError, match="shift_at"):
        simulate_access_phased(manifest, _cfg(), [(600.0, FLIP, None)])
    with pytest.raises(ValueError, match="strictly increasing"):
        simulate_access_phased(manifest, _cfg(),
                               [(300.0, FLIP, None), (200.0, FLIP, None)])
    with pytest.raises(ValueError, match="rate profile"):
        simulate_access_phased(manifest, _cfg(),
                               [(300.0, {"hot": "nope"}, None)])


def test_jittered_rates_deterministic(manifest):
    rng = np.random.default_rng(3)
    a = jittered_rates(manifest, _cfg(), rng)
    b = jittered_rates(manifest, _cfg(), np.random.default_rng(3))
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
