"""The utils shims over the telemetry layer: MetricsLog/StageTimer
(utils/logging.py) and the profiling warning path (utils/profiling.py)."""

import sys

import pytest

from cdrs_tpu.obs import JsonlSink, Telemetry, read_events
from cdrs_tpu.utils.logging import MetricsLog, StageTimer


def test_metricslog_repeated_key_keeps_both_values():
    """Regression (ISSUE 2 satellite): two timers under the same name in one
    process used to silently overwrite — e.g. two ``stream`` stages."""
    m = MetricsLog()
    with m.timer("stream"):
        pass
    with m.timer("stream"):
        pass
    rec = m.records["stream.seconds"]
    assert isinstance(rec, list) and len(rec) == 2
    assert all(v >= 0 for v in rec)
    # A third repetition appends rather than re-nesting.
    with m.timer("stream"):
        pass
    assert len(m.records["stream.seconds"]) == 3


def test_metricslog_increment_semantics():
    m = MetricsLog()
    assert m.increment("counter") == 1.0
    assert m.increment("counter", 2.5) == 3.5
    assert m.records["counter"] == 3.5
    m.record("listy", 1.0)
    m.record("listy", 2.0)
    with pytest.raises(TypeError, match="list"):
        m.increment("listy")


def test_metricslog_to_json_with_lists_and_none():
    import json

    m = MetricsLog()
    m.record("a", 1)
    m.record("a", 2)
    m.record("accuracy", None)  # planted_accuracy=None must serialize
    assert json.loads(m.to_json()) == {"a": [1.0, 2.0], "accuracy": None}


def test_stage_timer_opens_span_under_active_telemetry(tmp_path):
    p = str(tmp_path / "t.jsonl")
    with Telemetry(JsonlSink(p), meta=False) as tel:
        with tel.span("root"):
            m = MetricsLog()
            with m.timer("stage_x"):
                pass
    spans = {e["name"]: e for e in read_events(p) if e["kind"] == "span"}
    assert "stage_x" in spans
    assert spans["stage_x"]["parent"] == spans["root"]["id"]
    # the shim's flat record still works
    assert m.records["stage_x.seconds"] >= 0


def test_stage_timer_without_telemetry_is_plain():
    with StageTimer("solo") as t:
        pass
    assert t.elapsed >= 0


def test_trace_region_warns_without_jax(tmp_path, monkeypatch):
    """The no-jax fallback must degrade through warnings.warn (assertable),
    not a bare stderr print (ISSUE 2 satellite)."""
    from cdrs_tpu.utils.profiling import trace_region

    monkeypatch.setitem(sys.modules, "jax", None)  # import jax -> ImportError
    ran = False
    with pytest.warns(RuntimeWarning, match="no trace will be written"):
        with trace_region(str(tmp_path / "prof")):
            ran = True
    assert ran  # the body still executes — degradation, not failure


def test_trace_region_noop_without_dir(recwarn):
    from cdrs_tpu.utils.profiling import trace_region

    with trace_region(None):
        pass
    assert not [w for w in recwarn.list if issubclass(w.category,
                                                      RuntimeWarning)]
