"""Exportable replication plan (cluster/plan.py, VERDICT r4 #9).

The plan is the hook that lets the decision act on a REAL cluster (the
reference stands up a live HDFS but never applies its decided factors —
docker/hadoop.env:2 pins dfs.replication=1).
"""

import json
import subprocess

import numpy as np
import pytest

from cdrs_tpu.cluster import (PlanEntry, build_plan, read_plan_csv,
                              write_plan_csv, write_setrep_script)
from cdrs_tpu.config import ScoringConfig


def test_build_plan_uses_config_rf_table():
    cfg = ScoringConfig()
    entries = build_plan(["/a", "/b"], ["Hot", "Archival"], cfg)
    assert entries == [
        PlanEntry("/a", "Hot", cfg.replication_factors["Hot"]),
        PlanEntry("/b", "Archival", cfg.replication_factors["Archival"]),
    ]


def test_build_plan_rejects_unknown_category():
    with pytest.raises(ValueError, match="Sizzling"):
        build_plan(["/a"], ["Sizzling"], ScoringConfig())


def test_build_plan_explicit_rf_overrides_table():
    entries = build_plan(["/a", "/b"], ["Hot", "Hot"], rf=np.array([5, 1]))
    assert [e.rf for e in entries] == [5, 1]


def test_plan_csv_round_trip(tmp_path):
    entries = build_plan(
        [f"/data/file_{i:04d}.bin" for i in range(50)],
        ["Hot", "Moderate", "Shared", "Archival"] * 12 + ["Hot", "Shared"],
        ScoringConfig())
    p = tmp_path / "plan.csv"
    write_plan_csv(str(p), entries)
    assert read_plan_csv(str(p)) == entries


def test_setrep_script_groups_by_rf(tmp_path):
    entries = build_plan(
        [f"/f{i}" for i in range(10)],
        ["Hot"] * 4 + ["Archival"] * 6, ScoringConfig())
    p = tmp_path / "apply.sh"
    n = write_setrep_script(str(p), entries, batch=500)
    text = p.read_text()
    # One command per rf group at this size; every path present exactly once.
    assert n == 2 == text.count("hdfs dfs -setrep")
    for e in entries:
        assert f"'{e.path}'" in text
    # rf groups carry the right factor.
    cfg = ScoringConfig()
    assert f"-setrep {cfg.replication_factors['Archival']} " in text
    assert f"-setrep {cfg.replication_factors['Hot']} " in text


def test_setrep_script_batches_and_quotes(tmp_path):
    entries = [PlanEntry(f"/weird it's {i}", "Hot", 3) for i in range(7)]
    p = tmp_path / "apply.sh"
    n = write_setrep_script(str(p), entries, batch=3)
    assert n == 3  # ceil(7/3)
    # The script must parse as valid shell (quote-escaping correct).
    subprocess.run(["sh", "-n", str(p)], check=True)


def test_cli_evaluate_emit_plan_round_trip(tmp_path, capsys):
    """cdrs evaluate --emit_plan/--emit_setrep: plan matches the assignments
    the evaluation itself applied."""
    from cdrs_tpu.cli import main
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=30, seed=3))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=30, seed=3))
    mpath, apath = tmp_path / "m.csv", tmp_path / "a.log"
    manifest.write_csv(str(mpath))
    events.write_csv(str(apath), manifest)

    cats = ["Hot", "Shared", "Moderate"]
    assign = tmp_path / "assign.csv"
    with open(assign, "w") as f:
        f.write("path,cluster,category\n")
        for i, p in enumerate(manifest.paths):
            f.write(f"{p},0,{cats[i % 3]}\n")

    plan_p, setrep_p = tmp_path / "plan.csv", tmp_path / "apply.sh"
    rc = main(["evaluate", "--manifest", str(mpath), "--access_log",
               str(apath), "--assignments_csv", str(assign),
               "--emit_plan", str(plan_p), "--emit_setrep", str(setrep_p)])
    assert rc == 0
    json.loads(capsys.readouterr().out)  # metrics still printed

    cfg = ScoringConfig()
    entries = read_plan_csv(str(plan_p))
    assert len(entries) == 30
    by_path = {e.path: e for e in entries}
    for i, p in enumerate(manifest.paths):
        assert by_path[p].category == cats[i % 3]
        assert by_path[p].rf == cfg.replication_factors[cats[i % 3]]
    subprocess.run(["sh", "-n", str(setrep_p)], check=True)
