"""Scenario matrix (cdrs_tpu/scenarios): spec round-trip, fault
templates, harness invariants, legacy-bench preset reproduction against
the PINNED artifacts, sweep + history plumbing, CLI smoke."""

import json
import os

import numpy as np
import pytest

from cdrs_tpu.cli import main as cli_main
from cdrs_tpu.faults import FaultSchedule
from cdrs_tpu.scenarios import (
    PRESETS,
    ScenarioSpec,
    preset,
    random_cell,
    run_cell,
    suite_cells,
)
from cdrs_tpu.scenarios.sweep import run_cells

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- spec --------------------------------------------------------------------

def test_spec_json_roundtrip():
    spec = preset("rack-partition")
    d = spec.to_dict()
    json.loads(json.dumps(d))  # JSON-able
    back = ScenarioSpec.from_dict(d)
    assert back.to_dict() == d


def test_spec_validation():
    with pytest.raises(ValueError, match="workload kind"):
        ScenarioSpec(name="x", workload={"kind": "sawtooth"})
    with pytest.raises(ValueError, match="drift kind"):
        ScenarioSpec(name="x", drift={"kind": "nope"})
    with pytest.raises(ValueError, match="poisson workload only"):
        ScenarioSpec(name="x", workload={"kind": "diurnal"},
                     drift={"kind": "flip"})
    with pytest.raises(ValueError, match="scrub requires"):
        ScenarioSpec(name="x", scrub=1000)
    with pytest.raises(ValueError, match="unknown scenario spec keys"):
        ScenarioSpec.from_dict({"name": "x", "wat": 1})


def test_random_cells_deterministic():
    a = random_cell(0, SEED)
    b = random_cell(0, SEED)
    assert a.to_dict() == b.to_dict()
    c = random_cell(1, SEED)
    assert c.to_dict() != a.to_dict()


def test_ci_smoke_suite_shape():
    cells = suite_cells("ci-smoke", SEED)
    assert len(cells) >= 12
    names = {c.name for c in cells}
    # The five legacy smoke domains are all present, plus the new axes.
    assert {"chaos-kill", "rack-partition", "storage-ec", "serve-chaos",
            "integrity-scrub"} <= names
    assert any(c.resume_window is not None for c in cells)
    assert len(names) == len(cells)


# -- fault templates ---------------------------------------------------------

def test_cascade_template():
    s = FaultSchedule.cascade(["dn1", "dn2"], start=3, spacing=2,
                              recover_after=3)
    specs = [e.spec() for e in s]
    assert specs == ["crash:dn1@3", "crash:dn2@5", "recover:dn1@6",
                     "recover:dn2@8"]
    perm = FaultSchedule.cascade(["dn1"], start=0)
    assert [e.spec() for e in perm] == ["crash:dn1@0"]
    with pytest.raises(ValueError, match="spacing"):
        FaultSchedule.cascade(["dn1"], start=0, spacing=0)


def test_rolling_decommission_template():
    s = FaultSchedule.rolling_decommission(["dn2", "dn3"], start=4,
                                           spacing=4)
    assert [e.spec() for e in s] == ["decommission:dn2@4",
                                    "decommission:dn3@8"]


# -- legacy benches re-expressed: pinned-artifact reproduction ---------------

def test_preset_control_shift_reproduces_pinned_record():
    """The control_bench scenario re-expressed as a spec over the ONE
    harness reproduces the pinned controller headline bit-identically on
    the same seed (data/control_bench.json — ISSUE 10 acceptance)."""
    path = os.path.join(REPO, "data", "control_bench.json")
    if not os.path.exists(path):  # pragma: no cover
        pytest.skip("pinned artifact not present")
    with open(path, encoding="utf-8") as f:
        ref = json.load(f)["controller"]
    cell = run_cell(preset("control-shift"))
    assert cell["metrics"]["bytes_migrated_total"] == \
        ref["bytes_migrated_total"]
    assert cell["metrics"]["reclusters"] == ref["reclusters"]
    assert cell["ok"], cell["invariants"]


def test_preset_chaos_kill_reproduces_pinned_record():
    """Same for chaos_bench's kill-one-node scenario: repair traffic,
    loss count and the healed end state match data/chaos_bench.json
    exactly, and the cell's own invariants (zero loss, budget, sampled
    kill/resume bit-identity) hold."""
    path = os.path.join(REPO, "data", "chaos_bench.json")
    if not os.path.exists(path):  # pragma: no cover
        pytest.skip("pinned artifact not present")
    with open(path, encoding="utf-8") as f:
        ref = json.load(f)["recovery"]
    cell = run_cell(preset("chaos-kill"))
    m = cell["metrics"]
    assert m["repair_bytes_total"] == ref["repair_bytes_total"]
    assert m["files_lost_max"] == ref["files_lost_max"] == 0
    assert m["unavailable_reads"] == ref["unavailable_reads"]
    assert cell["invariants"]["resume_bit_identical"]
    assert cell["ok"], cell["invariants"]


# -- harness invariants ------------------------------------------------------

def _tiny(name="tiny", **kw) -> ScenarioSpec:
    base = dict(n_files=120, seed=SEED, duration=480.0, n_windows=8, k=8,
                nodes=("dn1", "dn2", "dn3", "dn4"))
    base.update(kw)
    return ScenarioSpec(name=name, **base)


def test_run_cell_green_and_records():
    cell = run_cell(_tiny(faults={"specs": ["crash:dn2@2-4"]},
                          serve={"policy": "p2c"}))
    assert cell["ok"], cell["invariants"]
    assert {"zero_lost_final", "budget_conserved",
            "slo_no_unavailable_final"} <= set(cell["invariants"])
    metrics = {r["metric"] for r in cell["bench_records"]}
    assert "scenario_tiny_churn_bytes" in metrics
    assert cell["repro"].startswith("python -m cdrs_tpu scenarios run")


def test_invariant_failure_detected_with_repro():
    """A cell designed to lose data (decommissions outrunning a starved
    repair budget) must go red with a repro line — the gate actually
    gates."""
    cell = run_cell(_tiny(
        name="doomed",
        faults={"template": "rolling_decommission",
                "nodes": ["dn2", "dn3"], "start": 1, "spacing": 1},
        budget_frac=0.0001))
    assert not cell["invariants"]["zero_lost_final"]
    assert not cell["ok"]
    assert "repro" in cell and "scenarios run" in cell["repro"]


def test_engagement_invariants_catch_vacuous_cells():
    """A fault axis that never fires inside the run (events scheduled
    past the horizon) must FAIL the gate, not pass every negative check
    vacuously — the Yuan-et-al. lesson applied to the gate itself."""
    cell = run_cell(_tiny(name="vacuous",
                          faults={"specs": ["crash:dn2@100"]}))
    assert cell["invariants"]["faults_engaged"] is False
    assert not cell["ok"]
    # Engaged axes report their engagement alongside the negative checks.
    live = run_cell(_tiny(name="live",
                          faults={"specs": ["corrupt:dn2@2:0.5",
                                            "crash:dn3@2-4"]},
                          serve={"policy": "p2c"}))
    assert live["invariants"]["faults_engaged"]
    assert live["invariants"]["corruption_engaged"]
    assert live["invariants"]["serve_engaged"]
    assert live["ok"], live["invariants"]


def test_resume_bit_identity_sampled():
    cell = run_cell(_tiny(name="resume",
                          faults={"specs": ["crash:dn2@2-5"]},
                          resume_window=3))
    assert cell["invariants"]["resume_bit_identical"]
    assert cell["ok"], cell["invariants"]


def test_budget_conservation_under_scrub_and_repair():
    """Repair + migration + scrub share ONE budget; the invariant holds
    with every consumer active at once."""
    cell = run_cell(_tiny(name="shared-budget",
                          duration=720.0, n_windows=12,
                          faults={"specs": ["corrupt:dn2@2:0.5",
                                            "crash:dn3@3-5"]},
                          scrub=50_000_000, budget_frac=0.5))
    assert cell["invariants"]["budget_conserved"]
    assert cell["invariants"]["zero_silent_loss"]
    assert cell["ok"], cell["invariants"]


# -- sweep -------------------------------------------------------------------

def test_sweep_artifact_and_history_idempotency(tmp_path):
    from cdrs_tpu.benchmarks.regress import load_history

    cells = [_tiny(name="s1", faults={"specs": ["crash:dn2@2-4"]}),
             _tiny(name="s2", seed=SEED + 1)]
    hist = str(tmp_path / "h.jsonl")
    out = run_cells(cells, suite=None, round_no=42, history=hist)
    assert out["ok"] and out["n_cells"] == 2
    assert out["history_appended"] == len(out["bench_records"]) > 0
    rows = load_history(hist)
    assert all(r["metric"].startswith("scenario_") for r in rows)
    assert all(r["round"] == 42 for r in rows)
    # Re-running the identical sweep appends nothing (the dedup key).
    again = run_cells(cells, suite=None, round_no=42, history=hist)
    assert again["history_appended"] == 0
    assert load_history(hist) == rows


# -- CLI ---------------------------------------------------------------------

def test_cli_scenarios_list(capsys):
    assert cli_main(["scenarios", "list"]) == 0
    out = capsys.readouterr().out
    assert "control-shift" in out and "ci-smoke" in out
    assert all(name in out for name in PRESETS)


def test_cli_scenarios_run_spec_and_errors(tmp_path, capsys):
    spec = _tiny(name="cli-cell").to_dict()
    path = tmp_path / "cell.json"
    path.write_text(json.dumps(spec))
    assert cli_main(["scenarios", "run", "--spec", str(path)]) == 0
    cell = json.loads(capsys.readouterr().out)
    assert cell["cell"] == "cli-cell" and cell["ok"]
    assert cli_main(["scenarios", "run"]) == 2
    assert cli_main(["scenarios", "run", "--cell", "nope"]) == 2


def test_cli_scenarios_run_suite_cell(capsys):
    rc = cli_main(["scenarios", "run", "--suite", "ci-smoke",
                   "--cell", "cascade"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["cell"] == "cascade"
    assert out["repro"].endswith("--cell cascade")


def test_suite_seed_shifts_preset_workloads_and_refuses_history():
    """A non-zero suite seed re-seeds every preset's workload (the CI
    multi-seed dimension: same invariants, different workloads) — and a
    shifted sweep must refuse to append history, whose per-cell
    baseline keys are defined at seed 0."""
    import pytest as _pytest

    base = {c.name: c for c in suite_cells("ci-smoke", 0)}
    shifted = {c.name: c for c in suite_cells("ci-smoke", 5)}
    for name, sp in base.items():
        if name.startswith("random-"):
            continue
        assert shifted[name].seed == sp.seed + 5
        assert getattr(shifted[name], "_preset", None) == \
            getattr(sp, "_preset", None)
    with _pytest.raises(ValueError, match="seed 0"):
        run_cells([_tiny(name="x")], seed=5, round_no=1,
                  history="/tmp/never-written.jsonl")


def test_suite_repro_carries_seed_and_random_names_encode_it():
    """A random cell is a function of (suite seed, index): the repro
    line must pin the seed, and the cell name (hence its history metric
    key) must encode it so different seeds' scenarios never alias."""
    from cdrs_tpu.scenarios.harness import repro_line

    spec = random_cell(1, 7)
    assert spec.name == "random-s7-1"
    line = repro_line(spec, suite="ci-smoke", suite_seed=7)
    assert "--seed 7" in line and line.endswith("--cell random-s7-1")
    cells = {c.name for c in suite_cells("ci-smoke", 7)}
    assert "random-s7-1" in cells and "random-s0-1" not in cells


def test_quick_bench_runs_do_not_append_history(tmp_path, monkeypatch):
    """--quick bench runs must never write the ledger: a smoke-scale
    row would dedup away the later real measurement (regress
    append_history keeps the FIRST row per key)."""
    import cdrs_tpu.benchmarks.plan_bench as pb

    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(
        pb, "run_plan_bench",
        lambda *a, **k: {"scales": [{"scale": "1k", "planner_speedup": 1.0,
                                     "migration_speedup": 1.0,
                                     "repair_speedup": 1.0,
                                     "decisions_identical": True}],
                         "end_to_end": {"overlap_bit_identical": True,
                                        "windows_per_sec_overlap": 1.0},
                         "criteria": {}, "bench_records": [
                             {"metric": "plan_planner_speedup_1k",
                              "value": 1.0, "unit": "x",
                              "backend": "numpy"}]})
    assert pb.main(["--quick", "--out", str(tmp_path / "o.json")]) == 0
    assert not (tmp_path / "data" / "bench_history.jsonl").exists()
    # A full run (no --quick) appends to the default ledger.
    assert pb.main(["--out", str(tmp_path / "o2.json")]) == 0
    assert (tmp_path / "data" / "bench_history.jsonl").exists()


def test_spec_repro_line_roundtrips():
    """The --spec repro line re-materializes the same cell."""
    from cdrs_tpu.scenarios.harness import repro_line

    spec = _tiny(name="rt", faults={"specs": ["crash:dn2@2-3"]})
    line = repro_line(spec)
    payload = line.split("--spec ", 1)[1].strip("'")
    assert ScenarioSpec.from_dict(json.loads(payload)).to_dict() == \
        spec.to_dict()


def test_presets_all_runnable_shapes():
    """Every preset builds its inputs (manifest/events/schedule/
    controller) without running the full loop — a cheap structural
    guard that no preset rots."""
    from cdrs_tpu.scenarios.harness import (
        _controller,
        build_events,
        build_schedule,
    )
    from cdrs_tpu.config import GeneratorConfig
    from cdrs_tpu.sim.generator import generate_population

    for name, spec in PRESETS.items():
        small = spec.replace(n_files=min(spec.n_files, 60), k=4)
        manifest = generate_population(GeneratorConfig(
            n_files=small.n_files, seed=small.seed, nodes=small.nodes))
        events, changed = build_events(small, manifest)
        assert len(events) > 0, name
        assert np.all(np.diff(events.ts) >= 0), name
        schedule = build_schedule(small)
        ctl = _controller(small, manifest, schedule)
        assert ctl.cfg.window_seconds == small.window_seconds, name
