"""Live operational plane (obs/httpz.py + obs/prom.py).

Covers the PR-18 surfaces end to end:

* the shared Prometheus renderer extracted into obs/prom.py — golden
  byte-for-byte against the historical ``cdrs metrics export``
  exposition, meta-series determinism, and the promtool-style lint;
* ObsServer unit lifecycle — readiness/health probe semantics, the
  snapshot-swap contract, 404s, the empty /debug/trace document;
* StreamDaemon integration through the in-process feed — snapshot
  invariant ``epochs_published == windows_processed == seq``, the
  concurrency hammer (scrapes racing republication see no torn reads),
  SIGTERM-drain readiness, and the /healthz flip on a page-severity
  alert with recovery;
* the consumer CLIs: ``cdrs status [--json]`` and
  ``cdrs metrics watch --url``.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from cdrs_tpu.cli import main as cdrs_main
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.daemon import StreamDaemon
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.obs import metrics_cli, prom
from cdrs_tpu.obs.alerts import AlertRule
from cdrs_tpu.obs.httpz import (
    EMPTY_SNAPSHOT,
    STATUSZ_WALL_KEYS,
    ObsServer,
    ObsSnapshot,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=150, seed=31))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=600.0, seed=32))
    return manifest, events


def _cfg(**kw):
    base = dict(window_seconds=120.0, backend="numpy",
                kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config())
    base.update(kw)
    return ControllerConfig(**base)


def _get(url: str):
    """(status_code, body) for one GET — 503s are data, not errors."""
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _synthetic_batches(n_files: int, sizes, window_seconds: float,
                       seed: int = 7):
    """One EventLog batch per window, ``sizes[w]`` events inside window
    ``w`` — the deterministic feed the lifecycle tests drive."""
    rng = np.random.default_rng(seed)
    batches = []
    for w, size in enumerate(sizes):
        ts = np.sort(rng.uniform(w * window_seconds,
                                 (w + 1) * window_seconds, size))
        batches.append(EventLog(
            ts=ts.astype(np.float64),
            path_id=rng.integers(0, n_files, size).astype(np.int32),
            op=np.zeros(size, dtype=np.int8),
            client_id=np.zeros(size, dtype=np.int32),
            clients=["c0"]))
    return batches


# -- obs/prom.py: the shared renderer ---------------------------------------

GOLDEN_EVENTS = [
    {"kind": "counter", "name": "reads.routed", "value": 12345, "run": "r1"},
    {"kind": "counter", "name": "jit.recompiles", "value": 3, "run": "r1"},
    {"kind": "gauge", "name": "serve.p99_ms", "value": 41.5},
    {"kind": "gauge", "name": "9weird name!", "value": 2.0},
    {"kind": "hist", "name": "plan.seconds", "value": 0.25},
    {"kind": "hist", "name": "plan.seconds", "value": 0.75},
    {"kind": "span", "name": "window", "dur": 1.5, "id": 1, "run": "r1"},
    {"kind": "hist_bulk", "name": "serve.latency_ms", "count": 4,
     "sum": 10.0, "min": 1.0, "max": 4.0,
     "buckets": [[1.0, 1], [3.0, 2], ["+Inf", 1]]},
    {"kind": "window", "window": 0, "durability": {"lost": 1}},
    {"kind": "window", "window": 1, "durability": {"lost": 1}},
]

# The exposition ``cdrs metrics export --format prometheus`` produced
# BEFORE the renderer moved to obs/prom.py — captured verbatim from the
# pre-refactor metrics_cli.prometheus_lines.  The refactor must keep
# every byte.
GOLDEN_TEXT = """\
# TYPE cdrs_jit_recompiles counter
cdrs_jit_recompiles 3
# TYPE cdrs_reads_routed counter
cdrs_reads_routed 12345
# TYPE cdrs_9weird_name_ gauge
cdrs_9weird_name_ 2
# TYPE cdrs_serve_p99_ms gauge
cdrs_serve_p99_ms 41.5
# TYPE cdrs_plan_seconds summary
cdrs_plan_seconds{quantile="0.5"} 0.25
cdrs_plan_seconds{quantile="0.95"} 0.75
cdrs_plan_seconds_sum 1
cdrs_plan_seconds_count 2
# TYPE cdrs_span_window_seconds summary
cdrs_span_window_seconds{quantile="0.5"} 1.5
cdrs_span_window_seconds{quantile="0.95"} 1.5
cdrs_span_window_seconds_sum 1.5
cdrs_span_window_seconds_count 1
# TYPE cdrs_serve_latency_ms histogram
cdrs_serve_latency_ms_bucket{le="1"} 1
cdrs_serve_latency_ms_bucket{le="3"} 3
cdrs_serve_latency_ms_bucket{le="+Inf"} 4
cdrs_serve_latency_ms_sum 10
cdrs_serve_latency_ms_count 4
# TYPE ALERTS gauge
ALERTS{alertname="files_lost",alertstate="firing",severity="page"} 1
ALERTS{alertname="durability_degraded",alertstate="firing",severity="ticket"} 1
"""


def test_prometheus_lines_golden_bytes():
    text = "\n".join(prom.prometheus_lines(GOLDEN_EVENTS)) + "\n"
    assert text == GOLDEN_TEXT


def test_textfile_export_is_a_thin_wrapper():
    # The CLI surface re-exports the SAME objects — not a parallel
    # implementation that could drift.
    assert metrics_cli.prometheus_lines is prom.prometheus_lines
    assert metrics_cli._prom_name is prom.prom_name


def test_export_cli_appends_meta_series(tmp_path, capsys):
    f = tmp_path / "m.jsonl"
    f.write_text("".join(json.dumps(e) + "\n" for e in GOLDEN_EVENTS))
    assert metrics_cli.main(["export", str(f)]) == 0
    text = capsys.readouterr().out
    assert text.startswith(GOLDEN_TEXT.rstrip("\n"))
    assert "# TYPE cdrs_process_start_time_seconds gauge" in text
    assert 'cdrs_build_info{version="' in text
    assert text.endswith("\n")
    assert prom.lint(text) == []


def test_meta_lines_deterministic_bytes():
    assert prom.meta_lines(start_time=123.4564, version="1.2.3") == [
        "# TYPE cdrs_process_start_time_seconds gauge",
        "cdrs_process_start_time_seconds 123.456",
        "# TYPE cdrs_build_info gauge",
        'cdrs_build_info{version="1.2.3"} 1',
    ]


def test_prom_name_sanitization():
    assert prom.prom_name("reads.routed") == "cdrs_reads_routed"
    assert prom.prom_name("9weird name!") == "cdrs_9weird_name_"
    assert prom.prom_name("9lead", prefix="") == "_9lead"


def test_lint_accepts_golden_and_meta():
    assert prom.lint(GOLDEN_TEXT) == []
    assert prom.lint("\n".join(prom.meta_lines()) + "\n") == []


@pytest.mark.parametrize("bad,needle", [
    ("cdrs_x 1\n", "no preceding TYPE"),
    ("# TYPE cdrs_x counter\ncdrs_x nope\n", "non-numeric"),
    ("# TYPE cdrs_x counter\n# TYPE cdrs_x gauge\ncdrs_x 1\n",
     "duplicate TYPE"),
    ("# TYPE cdrs_x counter\ncdrs_x 1", "end with a newline"),
    ('# TYPE cdrs_x counter\ncdrs_x{9bad="v"} 1\n', "bad label"),
    ("# TYPE cdrs_x counter\nnot a sample at all !\n", "unparseable"),
])
def test_lint_rejects_malformed(bad, needle):
    errs = prom.lint(bad)
    assert any(needle in e for e in errs), errs


# -- ObsServer unit lifecycle ------------------------------------------------

def test_server_probe_lifecycle():
    with ObsServer() as srv:
        code, body = _get(srv.url + "/")
        assert code == 200 and "/metrics" in body
        # Fresh server: no epoch yet -> unready, but alive -> healthy.
        code, body = _get(srv.url + "/readyz")
        assert code == 503 and "no placement epoch" in body
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and body == "ok\n"
        srv.set_ready(True)
        assert _get(srv.url + "/readyz") == (200, "ready\n")
        # Drain wins over ready, immediately.
        srv.set_draining(True)
        code, body = _get(srv.url + "/readyz")
        assert code == 503 and "draining" in body
        assert srv.readiness() == (False, "draining")
        code, _ = _get(srv.url + "/nope")
        assert code == 404


def test_server_health_trips_on_severe_alert_and_recovers():
    page = {"name": "files_lost", "severity": "page", "kind": "threshold",
            "firing": True, "fired": True, "since": 3, "streak": 2}
    ticket = dict(page, name="durability_degraded", severity="ticket")
    with ObsServer() as srv:
        srv.publish(ObsSnapshot(seq=1, alerts=(ticket,)))
        assert _get(srv.url + "/healthz")[0] == 200  # ticket never pages
        srv.publish(ObsSnapshot(seq=2, alerts=(page, ticket)))
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and "files_lost" in body
        # /metrics exposes BOTH firing alerts while health trips.
        _, text = _get(srv.url + "/metrics")
        assert 'ALERTS{alertname="files_lost"' in text
        assert 'ALERTS{alertname="durability_degraded"' in text
        # Recovery without restart: next snapshot clears the page.
        srv.publish(ObsSnapshot(seq=3, alerts=(ticket,)))
        assert _get(srv.url + "/healthz")[0] == 200


def test_server_health_trips_on_stale_heartbeat():
    with ObsServer(stale_after=0.0) as srv:
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and "tailer stalled" in body
        srv.stale_after = 60.0
        srv.heartbeat()
        assert _get(srv.url + "/healthz")[0] == 200


def test_empty_snapshot_surfaces_lint_clean():
    with ObsServer() as srv:
        code, text = _get(srv.url + "/metrics")
        assert code == 200
        assert prom.lint(text) == []
        assert "cdrs_obs_snapshot_seq 0" in text
        code, body = _get(srv.url + "/statusz")
        doc = json.loads(body)
        assert code == 200 and doc["seq"] == 0
        assert set(STATUSZ_WALL_KEYS) <= set(doc)
        code, body = _get(srv.url + "/debug/trace")
        assert code == 200
        assert json.loads(body) == {"displayTimeUnit": "ms",
                                    "traceEvents": []}


# -- daemon integration ------------------------------------------------------

def test_daemon_publishes_consistent_snapshots(workload):
    manifest, events = workload
    d = StreamDaemon(ReplicationController(manifest, _cfg()))
    with ObsServer() as srv:
        d.attach_http(srv)
        dig = d.run(events)
        snap = srv.snapshot
        # The no-torn-reads invariant, at rest: one snapshot per window,
        # one epoch per window.
        assert (snap.seq == snap.windows_processed
                == snap.epochs_published == dig["windows_processed"]
                == len(d.records) >= 2)
        assert snap.epoch_id == d.publisher.peek().epoch_id
        assert snap.events_ingested == len(events)
        assert snap.backlog_events == 0 and snap.backlog_bytes == 0
        # End of stream: no more epochs will publish -> not ready.
        assert srv.readiness()[0] is False

        _, text = _get(srv.url + "/metrics")
        assert prom.lint(text) == []
        assert f"cdrs_daemon_windows_processed {snap.seq}" in text
        assert f"cdrs_daemon_epochs_published {snap.seq}" in text
        assert "cdrs_daemon_decision_seconds_count" in text
        assert "cdrs_process_start_time_seconds" in text

        _, body = _get(srv.url + "/statusz")
        doc = json.loads(body)
        assert doc["seq"] == snap.seq
        assert doc["decision"]["count"] == len(d.decision_seconds)
        assert doc["stages"], "critical-path shares missing"
        share = sum(s["share"] for s in doc["stages"])
        assert share == pytest.approx(1.0, abs=1e-6)

        # Exemplars serve without a trace sink attached (retained heap).
        assert d.traced_decisions == 0
        _, body = _get(srv.url + "/debug/trace")
        trace = json.loads(body)
        names = {e["name"] for e in trace["traceEvents"]}
        assert any(n.startswith("decision w") for n in names)


def test_daemon_snapshot_attach_does_not_change_decisions(workload):
    manifest, events = workload
    ref = StreamDaemon(ReplicationController(manifest, _cfg()))
    ref.run(events)
    d = StreamDaemon(ReplicationController(manifest, _cfg()))
    with ObsServer() as srv:
        d.attach_http(srv)
        d.run(events)
    def strip(rs):
        return [{k: v for k, v in r.items() if k != "seconds"} for r in rs]

    assert strip(d.records) == strip(ref.records)


def test_concurrent_scrapes_never_tear(workload):
    manifest, _ = workload
    batches = _synthetic_batches(len(manifest), [250] * 20,
                                 window_seconds=60.0, seed=11)
    d = StreamDaemon(ReplicationController(manifest,
                                           _cfg(window_seconds=60.0)))
    done = threading.Event()
    errors: list[str] = []
    last_seq = {}

    def hammer(tid: int, path: str):
        while not done.is_set():
            code, body = _get(srv.url + path)
            if code != 200:
                errors.append(f"{path} -> {code}")
                return
            if path == "/statusz":
                doc = json.loads(body)
                seq, wp, ep = (doc["seq"], doc["windows_processed"],
                               doc["epochs_published"])
            else:
                vals = dict(
                    line.split(" ", 1) for line in body.splitlines()
                    if line and not line.startswith("#")
                    and "{" not in line)
                seq = float(vals["cdrs_obs_snapshot_seq"])
                wp = float(vals["cdrs_daemon_windows_processed"])
                ep = float(vals["cdrs_daemon_epochs_published"])
            if not (seq == wp == ep):
                errors.append(
                    f"torn {path}: seq={seq} windows={wp} epochs={ep}")
                return
            if seq < last_seq.get(tid, 0):
                errors.append(f"seq went backwards on {path}")
                return
            last_seq[tid] = seq

    with ObsServer() as srv:
        d.attach_http(srv)
        threads = [threading.Thread(target=hammer, args=(i, p))
                   for i, p in enumerate(["/metrics", "/statusz"] * 2)]
        for t in threads:
            t.start()
        d.run(iter(batches))
        done.set()
        for t in threads:
            t.join(timeout=10.0)
    assert errors == []
    assert max(last_seq.values()) >= 2  # scrapes actually saw progress


def test_readiness_flips_false_at_drain_request(workload):
    manifest, _ = workload
    batches = _synthetic_batches(len(manifest), [200] * 8,
                                 window_seconds=60.0, seed=13)
    d = StreamDaemon(ReplicationController(manifest,
                                           _cfg(window_seconds=60.0)))
    seen: list[tuple[str, int]] = []

    def feed():
        for k, b in enumerate(batches):
            if k == 4:
                # Ready by now: windows 0..k-2 processed, epochs live.
                seen.append(("pre", _get(srv.url + "/readyz")[0]))
                d.request_stop("SIGTERM")
                # Drain drops readiness IMMEDIATELY — before the daemon
                # finishes (or even starts) the in-flight window.
                seen.append(("drain", _get(srv.url + "/readyz")[0]))
                assert d._obs.readiness() == (False, "draining")
            yield b

    with ObsServer() as srv:
        d.attach_http(srv)
        dig = d.run(feed())
    assert dig["stop_reason"] == "SIGTERM"
    assert seen == [("pre", 200), ("drain", 503)]
    assert srv.readiness()[0] is False


def test_healthz_flips_on_page_alert_and_recovers(workload):
    manifest, _ = workload
    sizes = [120, 120, 500, 500, 120, 120]
    batches = _synthetic_batches(len(manifest), sizes,
                                 window_seconds=60.0, seed=17)
    rules = [AlertRule("hot_window", kind="threshold", field="n_events",
                       op=">", value=300, for_windows=1, severity="page")]
    d = StreamDaemon(ReplicationController(manifest,
                                           _cfg(window_seconds=60.0)),
                     rules=rules)
    health: list[tuple[int, int]] = []

    def feed():
        for k, b in enumerate(batches):
            if k >= 2:
                # Windows 0..k-2 are processed before batch k is pulled.
                health.append((k - 2, _get(srv.url + "/healthz")[0]))
            yield b

    with ObsServer() as srv:
        d.attach_http(srv)
        d.run(feed())
        # Trailing window 5 (120 events) processed at end of stream:
        # the alert resolved, health recovers without restart.
        code, _ = _get(srv.url + "/healthz")
        final = code
        snap = srv.snapshot
    assert health == [(0, 200), (1, 200), (2, 503), (3, 503)]
    assert final == 200
    assert snap.severe_firing() == ()
    rows = {a["name"]: a for a in snap.alerts}
    assert rows["hot_window"]["fired"] and not rows["hot_window"]["firing"]


# -- consumer CLIs -----------------------------------------------------------

@pytest.fixture()
def live_server(workload):
    manifest, events = workload
    d = StreamDaemon(ReplicationController(manifest, _cfg()))
    with ObsServer() as srv:
        d.attach_http(srv)
        d.run(events)
        srv.set_ready(True)  # frozen end state, presented as live
        yield srv


def test_cdrs_status_renders_block(live_server, capsys):
    assert cdrs_main(["status", live_server.url]) == 0
    out = capsys.readouterr().out
    assert f"cdrs daemon @ {live_server.url}" in out
    assert "state:    ready" in out
    assert "/readyz:  200 ready" in out
    assert "/healthz:  200 ok" in out


def test_cdrs_status_json_is_raw_statusz(live_server, capsys):
    assert cdrs_main(["status", live_server.url, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["seq"] == doc["windows_processed"] == doc["epochs_published"]


def test_cdrs_status_unreachable_is_exit_1(capsys):
    assert cdrs_main(["status", "127.0.0.1:1"]) == 1
    assert "unreachable" in capsys.readouterr().err


def test_metrics_watch_url_once(live_server, capsys):
    host_port = "{}:{}".format(*live_server.address)
    assert metrics_cli.main(["watch", "--url", host_port, "--once"]) == 0
    out = capsys.readouterr().out
    assert "cdrs daemon @ http://" + host_port in out
    assert "ingest:" in out and "decide:" in out


def test_metrics_watch_url_unreachable_is_exit_1(capsys):
    code = metrics_cli.main(["watch", "--url", "127.0.0.1:1", "--once"])
    assert code == 1
    assert "unreachable" in capsys.readouterr().out


def test_metrics_watch_requires_file_or_url(capsys):
    assert metrics_cli.main(["watch"]) == 2
    assert "--url" in capsys.readouterr().err
