"""Parity + sharding tests for the JAX KMeans backend.

Strategy per SURVEY.md §4: numerical parity NumPy-vs-JAX on identical inputs
(shared init via ``init_centroids``), plus multi-chip correctness on the
8-device virtual CPU mesh (conftest.py).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.ops.kmeans_np import kmeans, kmeans_plusplus_init, pairwise_sq_dists
from cdrs_tpu.ops.kmeans_jax import (
    kmeans_jax,
    kmeans_jax_full,
    pairwise_sq_dists_jax,
)


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(7)
    centers = rng.normal(size=(4, 5)) * 4.0
    X = np.concatenate([rng.normal(size=(250, 5)) * 0.5 + c for c in centers])
    return X


def test_pairwise_sq_dists_matches_numpy(blobs):
    C = blobs[:6]
    got = np.asarray(pairwise_sq_dists_jax(blobs, C))
    want = pairwise_sq_dists(blobs, C)
    np.testing.assert_allclose(got, want, atol=1e-8)


def test_lloyd_parity_with_numpy_same_init(blobs):
    init = kmeans_plusplus_init(blobs, 4, random_state=42)
    cn, ln = kmeans(blobs, 4, random_state=42, init_centroids=init)
    cj, lj = kmeans_jax(blobs, 4, seed=42, max_iter=100, init_centroids=init)
    np.testing.assert_allclose(np.asarray(cj), cn, atol=1e-10)
    assert (np.asarray(lj) == ln).all()


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_sharded_matches_single_device(blobs, ndev):
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    c1, l1 = kmeans_jax(blobs, 4, seed=0, max_iter=100, init_centroids=init)
    cn, ln = kmeans_jax(
        blobs, 4, seed=0, max_iter=100, init_centroids=init,
        mesh_shape={"data": ndev},
    )
    np.testing.assert_allclose(np.asarray(cn), np.asarray(c1), atol=1e-8)
    assert (np.asarray(ln) == np.asarray(l1)).all()


def test_uneven_shard_padding(blobs):
    X = blobs[:997]  # not divisible by 8
    init = kmeans_plusplus_init(X, 4, random_state=0)
    c1, l1 = kmeans_jax(X, 4, seed=0, max_iter=100, init_centroids=init)
    c8, l8 = kmeans_jax(
        X, 4, seed=0, max_iter=100, init_centroids=init, mesh_shape={"data": 8}
    )
    assert np.asarray(l8).shape == (997,)
    np.testing.assert_allclose(np.asarray(c8), np.asarray(c1), atol=1e-8)
    assert (np.asarray(l8) == np.asarray(l1)).all()


def test_d2_init_quality(blobs):
    """On-device D² init should land one centroid near each planted blob."""
    centroids, labels, it, shift = kmeans_jax_full(
        blobs, 4, seed=3, max_iter=100, mesh_shape={"data": 8}
    )
    centroids = np.asarray(centroids)
    # Every point should be close to its centroid (tight blobs, sigma=.5).
    d = pairwise_sq_dists(blobs, centroids)
    inertia = d[np.arange(len(blobs)), np.asarray(labels)].mean()
    assert inertia < 3.0  # ~ d * sigma^2 = 5 * 0.25; generous bound
    assert len(np.unique(np.asarray(labels))) == 4
    assert shift < 1e-4


@pytest.mark.parametrize("mesh", [None, {"data": 8}, {"data": 4, "model": 2}])
def test_kmeans_par_init_quality(blobs, mesh):
    """kmeans|| init + Lloyd must recover the planted blobs as well as D²
    (SURVEY.md §7.4: the documented oversampling alternative)."""
    from cdrs_tpu.ops.kmeans_np import pairwise_sq_dists

    centroids, labels, it, shift = kmeans_jax_full(
        blobs, 4, seed=3, max_iter=100, mesh_shape=mesh,
        init_method="kmeans||",
    )
    centroids = np.asarray(centroids)
    d = pairwise_sq_dists(blobs, centroids)
    inertia = d[np.arange(len(blobs)), np.asarray(labels)].mean()
    assert inertia < 3.0  # same bound as the D² quality test
    assert len(np.unique(np.asarray(labels))) == 4
    assert shift < 1e-4


def test_kmeans_par_deterministic(blobs):
    a = kmeans_jax_full(blobs, 4, seed=9, max_iter=20,
                        mesh_shape={"data": 8}, init_method="kmeans||")
    b = kmeans_jax_full(blobs, 4, seed=9, max_iter=20,
                        mesh_shape={"data": 8}, init_method="kmeans||")
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_kmeans_par_rejects_tiny_shards():
    """per-round sample > shard rows must fail with a clear message."""
    X = np.random.default_rng(0).normal(size=(64, 3))
    with pytest.raises(ValueError, match="kmeans"):
        kmeans_jax_full(X, 32, seed=0, max_iter=5, mesh_shape={"data": 8},
                        init_method="kmeans||")


def test_unknown_init_method_raises(blobs):
    with pytest.raises(ValueError, match="init_method"):
        kmeans_jax_full(blobs, 4, init_method="magic")


def test_block_scalars_false_returns_device_scalars(blobs):
    """block_scalars=False skips the scalar fetch: (it, shift) come back as
    device arrays with identical values, centroids/labels unchanged."""
    import jax

    a = kmeans_jax_full(blobs, 4, seed=5, max_iter=10, tol=0.0)
    c, lab, it, shift = kmeans_jax_full(blobs, 4, seed=5, max_iter=10,
                                        tol=0.0, block_scalars=False)
    assert isinstance(it, jax.Array) and isinstance(shift, jax.Array)
    np.testing.assert_array_equal(np.asarray(c), np.asarray(a[0]))
    np.testing.assert_array_equal(np.asarray(lab), np.asarray(a[1]))
    assert int(it) == a[2]
    assert float(shift) == a[3]


def test_resolve_init_method_auto_by_k():
    """auto = d2 below k=256, kmeans|| at and above (VERDICT r4 #4)."""
    from cdrs_tpu.ops.kmeans_jax import (AUTO_INIT_KMEANS_PAR_MIN_K,
                                         resolve_init_method)

    assert AUTO_INIT_KMEANS_PAR_MIN_K == 256
    assert resolve_init_method("auto", 4) == "d2"
    assert resolve_init_method("auto", 255) == "d2"
    assert resolve_init_method("auto", 256) == "kmeans||"
    assert resolve_init_method("auto", 1024) == "kmeans||"
    assert resolve_init_method("d2", 1024) == "d2"
    assert resolve_init_method("kmeans||", 4) == "kmeans||"


def test_auto_init_matches_resolved_method(blobs):
    """init_method='auto' at small k runs exactly the d2 path."""
    a = kmeans_jax_full(blobs, 4, seed=5, max_iter=20, init_method="auto")
    b = kmeans_jax_full(blobs, 4, seed=5, max_iter=20, init_method="d2")
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_auto_init_falls_back_on_tiny_shards():
    """auto at k >= 256 with an infeasible kmeans|| oversample must fall
    back to d2 instead of raising (explicit 'kmeans||' still raises)."""
    X = np.random.default_rng(0).normal(size=(512, 3))
    c, lab, _, _ = kmeans_jax_full(X, 256, seed=0, max_iter=3,
                                   mesh_shape={"data": 8},
                                   init_method="auto")
    d2 = kmeans_jax_full(X, 256, seed=0, max_iter=3, mesh_shape={"data": 8},
                         init_method="d2")
    np.testing.assert_array_equal(np.asarray(c), np.asarray(d2[0]))


def test_empty_cluster_reseed_deterministic():
    """k=4 on 4 distinct points with a far-away init forces reseeds; results
    must be reproducible from the seed (fixes reference quirk §6.1.2)."""
    X = np.array([[0.0, 0], [10, 0], [0, 10], [10, 10]])
    # all points -> cluster argmin ties
    init = np.full((4, 2), 100.0) + np.arange(4)[:, None]
    r1 = kmeans_jax(X, 4, seed=5, max_iter=50, init_centroids=init)
    r2 = kmeans_jax(X, 4, seed=5, max_iter=50, init_centroids=init)
    np.testing.assert_array_equal(np.asarray(r1[0]), np.asarray(r2[0]))
    # converged solution must cover all 4 points as singleton clusters
    assert sorted(np.asarray(r1[1]).tolist()) == sorted(
        np.unique(np.asarray(r1[1])).tolist()
    )


def test_k_exceeds_n_raises():
    with pytest.raises(ValueError):
        kmeans_jax(np.zeros((3, 2)), 5)


def test_2d_mesh_with_chunking(blobs):
    """chunk_rows must be honored on the (data, model) mesh (tiled distances)."""
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    c1, l1 = kmeans_jax(blobs, 4, seed=0, max_iter=100, init_centroids=init)
    c2, l2 = kmeans_jax(
        blobs, 4, seed=0, max_iter=100, init_centroids=init,
        mesh_shape={"data": 2, "model": 2}, chunk_rows=64,
    )
    np.testing.assert_allclose(np.asarray(c2), np.asarray(c1), atol=1e-8)
    assert (np.asarray(l2) == np.asarray(l1)).all()


def test_device_array_n_valid(blobs):
    """Pre-padded device arrays: padding rows excluded via n_valid."""
    import jax.numpy as jnp

    n = 997
    X = blobs[:n]
    pad = np.zeros((3, X.shape[1]))
    Xd = jnp.asarray(np.concatenate([X, pad]))  # 1000 rows, 3 padding
    init = kmeans_plusplus_init(X, 4, random_state=0)
    c1, l1 = kmeans_jax(X, 4, seed=0, max_iter=100, init_centroids=init)
    c2, l2, it2, _ = kmeans_jax_full(
        Xd, 4, seed=0, max_iter=100, init_centroids=init,
        mesh_shape={"data": 4}, n_valid=n,
    )
    assert np.asarray(l2).shape == (n,)
    np.testing.assert_allclose(np.asarray(c2), np.asarray(c1), atol=1e-8)
    assert (np.asarray(l2) == np.asarray(l1)).all()


def test_minibatch_counts_are_integer():
    """ADVICE r2: per-center totals accumulate exactly (int32), not f32."""
    import jax.numpy as jnp

    from cdrs_tpu.ops.kmeans_stream import MiniBatchKMeans

    rng = np.random.default_rng(7)
    mb = MiniBatchKMeans(k=4, seed=0)
    for _ in range(3):
        mb.partial_fit(rng.normal(size=(256, 4)).astype(np.float32))
    assert mb.state.counts.dtype == jnp.int32
    assert int(mb.state.counts.sum()) == 3 * 256


def test_minibatch_first_batch_smaller_than_k_raises():
    from cdrs_tpu.ops.kmeans_stream import minibatch_init

    X = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="rows < k"):
        minibatch_init(X, k=8, seed=0)


def test_model_minibatch_batch_size_below_k_raises():
    from cdrs_tpu.config import KMeansConfig
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    X = np.random.default_rng(1).normal(size=(64, 3)).astype(np.float32)
    model = ReplicationPolicyModel(
        KMeansConfig(k=16, batch_size=8, seed=0), backend="jax")
    with pytest.raises(ValueError, match="batch_size"):
        model.cluster(X)
