"""Overload resilience (daemon/brownout.py + daemon/core.py backpressure
+ daemon/supervise.py + scenarios triage): ladder semantics, lag-driven
coalescing determinism, brownout checkpoint/resume bit-equality,
crash-ANYWHERE (kill -9) recovery via a SIGKILL-injecting subprocess
worker, the crash supervisor, the daemon_lagging alert, and the
violation-triage promotion path.

``CDRS_CHAOS_SEED`` varies the workload seeds — CI's overload smoke
sweeps 0/1/2 so the crash-anywhere contract is not a single-seed
accident.
"""

import json
import os
import signal
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import overload_worker
from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.daemon import RUNGS, BrownoutConfig, BrownoutLadder, supervise
from cdrs_tpu.obs.alerts import evaluate_records
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))


def _strip(records):
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """One pre-written overfed binary log + manifest CSV shared by every
    daemon run in this module (the log is never mutated)."""
    d = tmp_path_factory.mktemp("overload")
    manifest = generate_population(
        GeneratorConfig(n_files=120, seed=31 + SEED))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=1800.0, seed=32 + SEED))
    mpath = str(d / "m.csv")
    manifest.write_csv(mpath)
    lpath = str(d / "ev.cdrsb")
    # Small blocks: fine-grained cursor positions, so kill points land
    # mid-window rather than on batch boundaries.
    events.write_binary(lpath, manifest, block_rows=256)
    return str(d), mpath, lpath


# -- brownout ladder (pure state machine) -----------------------------------

def test_brownout_config_validation():
    with pytest.raises(ValueError, match="cover all 5 rungs"):
        BrownoutConfig(engage=(1.0, 2.0))
    with pytest.raises(ValueError, match="non-decreasing"):
        BrownoutConfig(engage=(2.0, 1.0, 3.0, 4.0, 5.0))
    with pytest.raises(ValueError, match="strictly below"):
        BrownoutConfig(release=(2.0, 1.5, 2.0, 3.0, 4.0))
    with pytest.raises(ValueError, match="hold"):
        BrownoutConfig(hold=0)
    with pytest.raises(ValueError, match="shed_fraction"):
        BrownoutConfig(shed_fraction=1.5)
    with pytest.raises(ValueError, match="coalesce_max"):
        BrownoutConfig(coalesce_max=1)


def test_ladder_engages_in_order_and_releases_hysteretically():
    lad = BrownoutLadder(BrownoutConfig(hold=2))
    # A lag spike through rung 3's threshold engages three rungs AT ONCE.
    ts = lad.step(0, 5.0)
    assert [t["rung"] for t in ts] == list(RUNGS[:3])
    assert all(t["state"] == "engage" for t in ts)
    assert lad.modes() == frozenset(RUNGS[:3])
    # Calm windows release ONE rung per `hold` dwell, top rung first.
    assert lad.step(1, 0.5) == []          # calm 1/2
    ts = lad.step(2, 0.5)                  # calm 2/2 -> release
    assert [(t["rung"], t["state"]) for t in ts] == [("cap_trace",
                                                      "release")]
    # A relapse above the CURRENT rung's release bound resets the dwell.
    assert lad.step(3, 1.9) == []
    assert lad.calm == 0
    assert lad.level == 2


def test_ladder_burn_trip_wire_engages_whole_ladder():
    lad = BrownoutLadder(BrownoutConfig(burn_engage=2.0))
    ts = lad.step(0, 0.0, slo_burn=2.5)    # zero lag, burning budget
    assert [t["rung"] for t in ts] == list(RUNGS)
    assert lad.level == len(RUNGS)
    # The burn holding high blocks release even at zero lag.
    assert lad.step(1, 0.0, slo_burn=2.5) == []
    assert lad.calm == 0


def test_ladder_state_roundtrip():
    lad = BrownoutLadder(BrownoutConfig())
    lad.step(0, 4.5)
    lad.step(1, 0.0)
    fresh = BrownoutLadder(BrownoutConfig())
    fresh.load_state_dict(lad.state_dict())
    assert (fresh.level, fresh.calm) == (lad.level, lad.calm)
    fresh.load_state_dict({"level": 99, "calm": -3})  # clamped, not trusted
    assert (fresh.level, fresh.calm) == (len(RUNGS), 0)


# -- overloaded daemon: coalescing + determinism ----------------------------

def test_overfed_daemon_coalesces_deterministically(corpus):
    """A pre-written (maximally overfed) log: the ladder must engage,
    coalescing must merge windows mass-conservingly, lag must drain to
    zero, and a double run must be bit-identical — the decision-
    reproducibility contract of degraded operation."""
    _d, mpath, lpath = corpus
    runs = []
    for _ in range(2):
        dm = overload_worker.make_daemon(mpath, brownout=True,
                                         checkpoint_every=10**6)
        dig = dm.run(lpath)
        runs.append((dm, dig))
    d1, dig1 = runs[0]
    d2, _dig2 = runs[1]
    assert _strip(d1.records) == _strip(d2.records)
    assert d1.brownout_log == d2.brownout_log

    recs = d1.records
    # The ladder engaged (overfed log => immediate lag spike) and the
    # coalesce rung actually merged windows.
    assert dig1["brownout"]["level"] >= 1
    assert any(t["state"] == "engage" for t in d1.brownout_log)
    assert any(r["daemon"]["coalesced"] > 1 for r in recs)
    assert dig1["brownout"]["windows_coalesced"] > 0
    # Mass conservation: merged decisions still fold every event once.
    assert sum(r["n_events"] for r in recs) == d1.events_ingested
    # One epoch per DECISION (the /statusz invariant, under coalescing).
    assert dig1["epochs_published"] == dig1["windows_processed"] \
        == len(recs)
    # The cursor only advances, so lag over a static log is monotone
    # non-increasing and fully drained at end of stream.
    lags = [r["daemon"]["lag_bytes"] for r in recs]
    assert lags == sorted(lags, reverse=True)
    assert dig1["lag"]["bytes"] == 0 and dig1["lag"]["windows"] == 0.0
    # Degraded-mode levers actually pulled while engaged: deferred
    # scrub windows and explicitly-shed reads are reported per record.
    assert any(r.get("scrub", {}).get("deferred") for r in recs)
    lvl5 = [r for r in recs if r["daemon"]["brownout_level"]
            >= len(RUNGS)]
    if lvl5:
        assert all(r.get("reads_shed", 0) > 0 for r in lvl5
                   if r["n_reads"] > 100)
        # Bounded shed: ~shed_fraction of the window's reads, never all.
        for r in lvl5:
            assert r.get("reads_shed", 0) < r["n_reads"]


def test_brownout_daemon_off_matches_no_daemon_key(corpus):
    """brownout=None (the default) must not grow any record schema:
    the conditional keys protect every pinned artifact."""
    _d, mpath, lpath = corpus
    dm = overload_worker.make_daemon(mpath, brownout=False,
                                     checkpoint_every=10**6)
    dig = dm.run(lpath)
    assert all("daemon" not in r for r in dm.records)
    assert all("deferred" not in r.get("scrub", {}) for r in dm.records)
    assert "brownout" not in dig and "lag" not in dig


def test_brownout_resume_bit_identical_at_every_stop(corpus, tmp_path):
    """Graceful stop + resume under an ENGAGED ladder: the checkpointed
    (ladder, lag, estimator) state must make the joined record stream
    exactly the uninterrupted run's, at every stop point."""
    _d, mpath, lpath = corpus
    full = overload_worker.make_daemon(mpath, brownout=True)
    full.run(lpath)
    n = len(full.records)
    assert n >= 3
    for stop in (1, max(1, n // 2), n - 1):
        ck = str(tmp_path / f"ck{stop}.npz")
        d1 = overload_worker.make_daemon(mpath, brownout=True,
                                         max_windows=stop)
        d1.run(lpath, checkpoint_path=ck)
        d2 = overload_worker.make_daemon(mpath, brownout=True)
        dig2 = d2.run(lpath, checkpoint_path=ck)
        assert _strip(d1.records) + _strip(d2.records) \
            == _strip(full.records), f"stop={stop}"
        assert dig2["epochs_published"] == n


# -- crash-anywhere: kill -9 fuzz -------------------------------------------

def _windows(path):
    out = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue  # torn tail of a SIGKILLed writer
            if e.get("kind") == "window":
                out.append({k: v for k, v in e.items()
                            if k != "seconds"})
    return out


@pytest.mark.parametrize("brownout", [False, True])
def test_kill9_anywhere_resumes_decision_identical(corpus, tmp_path,
                                                   brownout):
    """SIGKILL at seeded (decision, stage) points — before a decision,
    after the decision but before ANY bookkeeping, right after a
    checkpoint lands — then resume: the deduplicated stitched window
    stream must equal the uninterrupted run's exactly (which the
    graceful-stop test above ties to the SIGTERM path), epoch ids must
    never re-publish, and the final plan state must match."""
    _d, mpath, lpath = corpus
    refm = str(tmp_path / "ref.jsonl")
    ref = overload_worker.make_daemon(mpath, brownout=brownout)
    refdig = ref.run(lpath, metrics_path=refm)
    n = len(ref.records)

    rng = np.random.default_rng([SEED, 20, int(brownout)])
    points = [("pre", int(rng.integers(1, n))),
              ("post", int(rng.integers(1, n))),
              ("save", int(rng.integers(0, n - 1)))]
    for stage, kn in points:
        tag = f"{stage}{kn}"
        ck = str(tmp_path / f"{tag}.npz")
        m1 = str(tmp_path / f"{tag}_kill.jsonl")
        m2 = str(tmp_path / f"{tag}_resume.jsonl")
        cmd = [sys.executable,
               os.path.join(os.path.dirname(__file__),
                            "overload_worker.py"),
               "--manifest", mpath, "--log", lpath,
               "--checkpoint", ck, "--metrics", m1,
               "--kill", f"{kn}:{stage}"]
        if brownout:
            cmd.append("--brownout")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == -signal.SIGKILL, \
            (stage, kn, proc.returncode, proc.stderr[-2000:])

        d2 = overload_worker.make_daemon(mpath, brownout=brownout)
        dig2 = d2.run(lpath, checkpoint_path=ck, metrics_path=m2)
        # Stitch + dedup (the killed run may have emitted records past
        # its last durable checkpoint; the resume re-decides them — and
        # both copies must be byte-equal, or the dedup would lie).
        stitched = {}
        for r in _windows(m1) + _windows(m2):
            if r["window"] in stitched:
                assert stitched[r["window"]] == r, \
                    f"{tag}: window {r['window']} re-decided differently"
            stitched[r["window"]] = r
        want = {r["window"]: r for r in _windows(refm)}
        assert stitched == want, f"{tag}: stitched stream != reference"
        # No re-published epoch ids: the resumed publisher continues the
        # uninterrupted sequence exactly.
        assert dig2["epochs_published"] == refdig["epochs_published"]
        np.testing.assert_array_equal(d2.controller.current_rf,
                                      ref.controller.current_rf)
        np.testing.assert_array_equal(d2.controller.current_cat,
                                      ref.controller.current_cat)


# -- supervisor --------------------------------------------------------------

def test_supervisor_restarts_then_succeeds(tmp_path):
    """A child that crashes twice then exits 0: the supervisor restarts
    it (capped backoff) and reports the eventual clean exit."""
    counter = tmp_path / "n.txt"
    prog = ("import pathlib, sys; p = pathlib.Path(r'%s'); "
            "n = int(p.read_text() or 0) if p.exists() else 0; "
            "p.write_text(str(n + 1)); sys.exit(0 if n >= 2 else 7)"
            % counter)
    lines = []
    rc = supervise([sys.executable, "-c", prog], max_restarts=5,
                   backoff_base=0.01, backoff_cap=0.05,
                   log=lines.append)
    assert rc == 0
    assert counter.read_text() == "3"
    assert sum("restarting in" in ln for ln in lines) == 2


def test_supervisor_gives_up_on_crash_loop():
    lines = []
    rc = supervise([sys.executable, "-c", "import sys; sys.exit(3)"],
                   max_restarts=3, backoff_base=0.01, backoff_cap=0.02,
                   log=lines.append)
    assert rc == 3
    assert any("giving up" in ln for ln in lines)


def test_supervisor_validates_args():
    with pytest.raises(ValueError, match="max_restarts"):
        supervise(["true"], max_restarts=0)
    with pytest.raises(ValueError, match="backoff"):
        supervise(["true"], backoff_base=2.0, backoff_cap=1.0)


def test_cli_supervise_strips_flags_and_reexecs(monkeypatch):
    """`cdrs daemon --supervise` must re-exec itself WITHOUT the
    supervision flags (child recursion would fork-bomb)."""
    import cdrs_tpu.daemon as daemon_pkg
    from cdrs_tpu import cli

    seen = {}

    def fake(child_argv, *, max_restarts):
        seen["argv"] = child_argv
        seen["max_restarts"] = max_restarts
        return 0

    # _cmd_daemon does `from .daemon import supervise` at call time,
    # so patching the package attribute intercepts the re-exec.
    monkeypatch.setattr(daemon_pkg, "supervise", fake)
    argv = ["daemon", "--manifest", "m.csv", "--access_log", "a.cdrsb",
            "--supervise", "--max_restarts", "7", "--brownout"]
    monkeypatch.setattr(sys, "argv", ["cdrs"] + argv)
    rc = cli.main(argv)
    assert rc == 0
    assert seen["max_restarts"] == 7
    tail = seen["argv"][3:]  # python -m cdrs_tpu ...
    assert "--supervise" not in tail and "--max_restarts" not in tail
    assert "7" not in tail
    assert "--brownout" in tail


# -- daemon_lagging alert ----------------------------------------------------

def test_daemon_lagging_alert_fires_on_sustained_lag():
    base = {"kind": "window", "n_events": 1}
    recs = [{**base, "window": w,
             "daemon": {"lag_windows": lag}}
            for w, lag in enumerate([0.5, 2.5, 3.0, 3.1, 1.0])]
    res = {r["name"]: r for r in evaluate_records(recs)}
    lagging = res["daemon_lagging"]
    assert lagging["fired"] and not lagging["firing"]
    assert lagging["since"] == 2  # 2 consecutive windows >= 2.0
    # Records WITHOUT the daemon key (every batch run) never match.
    silent = [{**base, "window": w} for w in range(5)]
    res = {r["name"]: r for r in evaluate_records(silent)}
    assert not res["daemon_lagging"]["fired"]


# -- /healthz under brownout -------------------------------------------------

def test_healthz_reports_degraded_but_stays_200():
    from cdrs_tpu.obs.httpz import ObsServer, ObsSnapshot

    with ObsServer() as srv:
        srv.publish(ObsSnapshot(seq=1, brownout_level=2,
                                brownout_rungs=RUNGS[:2]))
        with urllib.request.urlopen(srv.url + "/healthz",
                                    timeout=5) as r:
            body = r.read().decode()
            assert r.status == 200
        assert "degraded: rung 2" in body
        assert "defer_scrub" in body
        with urllib.request.urlopen(srv.url + "/statusz",
                                    timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["brownout"]["level"] == 2
        assert doc["brownout"]["rungs"] == list(RUNGS[:2])
        assert doc["lag"] == {"bytes": 0, "blocks": 0.0, "seconds": 0.0,
                              "windows": 0.0}
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=5) as r:
            text = r.read().decode()
        assert "cdrs_daemon_brownout_level 2" in text
        assert "cdrs_daemon_lag_windows 0" in text


# -- triage + extra-cells ----------------------------------------------------

def test_triage_promotes_green_violations(tmp_path):
    from cdrs_tpu.scenarios import preset
    from cdrs_tpu.scenarios.search import triage_corpus

    corpus = tmp_path / "corpus"
    vdir = corpus / "violations"
    vdir.mkdir(parents=True)
    spec = preset("chaos-kill").to_dict()
    (vdir / "search-s0-deadbeef-bad.json").write_text(json.dumps({
        "name": "search-s0-deadbeef-bad", "spec": spec,
        "shrunk": {"spec": spec}}))
    out = triage_corpus(str(corpus))
    assert out["ok"] and out["n_violations"] == 1
    assert out["names"] == ["triage-s0-deadbeef-bad"]
    assert out["cells"][0]["name"] == "triage-s0-deadbeef-bad"
    assert out["results"][0]["source"] == "search-s0-deadbeef-bad"


def test_triage_flags_still_red_violations(tmp_path):
    from cdrs_tpu.scenarios.search import (planted_violation_spec,
                                           triage_corpus)

    corpus = tmp_path / "corpus"
    vdir = corpus / "violations"
    vdir.mkdir(parents=True)
    (vdir / "search-s0-00000000-bad.json").write_text(json.dumps({
        "name": "search-s0-00000000-bad",
        "spec": planted_violation_spec().to_dict()}))
    out = triage_corpus(str(corpus))
    assert not out["ok"]
    assert out["results"][0]["failed"]  # names the violated invariants


def test_load_extra_cells_applies_names_and_validates(tmp_path):
    from cdrs_tpu.scenarios import preset
    from cdrs_tpu.scenarios.sweep import load_extra_cells

    doc = {"cells": [preset("chaos-kill").to_dict()],
           "names": ["triage-s0-feedface"]}
    p = tmp_path / "triage.json"
    p.write_text(json.dumps(doc))
    specs = load_extra_cells([str(p)])
    assert [s.name for s in specs] == ["triage-s0-feedface"]
    with pytest.raises(ValueError, match="cannot read"):
        load_extra_cells([str(tmp_path / "missing.json")])
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError, match="'cells' list"):
        load_extra_cells([str(bad)])


def test_committed_corpus_files_load_as_extra_cells():
    """The committed distilled.json + triage.json must stay loadable —
    CI feeds them to every ci-smoke sweep."""
    from cdrs_tpu.scenarios.sweep import load_extra_cells

    paths = ["data/search_corpus/distilled.json",
             "data/search_corpus/triage.json"]
    specs = load_extra_cells([p for p in paths if os.path.exists(p)])
    assert specs
    assert all(s.name.startswith(("search-", "triage-")) for s in specs)
