"""End-to-end decision tracing (obs/trace.py + daemon wiring): exact
segment reconciliation, deterministic trace ids across double runs and
SIGTERM/checkpoint/resume stitches, byte-stable canonical exports,
tail-sampled exemplars, the bounded decision-latency reservoir, and the
`cdrs trace` CLI surfaces."""

import io
import json
import os

import numpy as np
import pytest

from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.daemon import DaemonConfig, StreamDaemon
from cdrs_tpu.obs import trace as trace_mod
from cdrs_tpu.obs.aggregate import (
    collect,
    critical_path_digest,
    daemon_digest,
)
from cdrs_tpu.obs.trace import (
    build_span_tree,
    chrome_trace,
    decision_trace_id,
    mint_batch,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=150, seed=31))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=600.0, seed=32))
    return manifest, events


def _cfg(**kw):
    base = dict(window_seconds=120.0, backend="numpy",
                kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config())
    base.update(kw)
    return ControllerConfig(**base)


def _run_traced(workload, tmp_path, name="m.jsonl", dcfg=None, **run_kw):
    manifest, events = workload
    ctl = ReplicationController(manifest, _cfg())
    d = StreamDaemon(ctl, dcfg or DaemonConfig())
    log = tmp_path / "events.cdrsb"
    if not log.exists():
        events.write_binary(str(log), manifest)
    metrics = tmp_path / name
    dig = d.run(str(log), metrics_path=str(metrics), **run_kw)
    with open(metrics, encoding="utf-8") as f:
        evs = [json.loads(line) for line in f]
    return d, dig, evs, metrics


def _decisions(evs):
    return [e for e in evs if e.get("kind") == "decision_trace"]


# -- context + ids ----------------------------------------------------------

def test_trace_id_is_window_deterministic():
    assert decision_trace_id(0) == "d000000"
    assert decision_trace_id(7) == "d000007"
    assert decision_trace_id(123456) == "d123456"


def test_mint_batch_carries_cursor_and_stamp():
    tc = mint_batch(4096, 17)
    assert (tc.offset, tc.skip) == (4096, 17)
    assert tc.ingest_ns > 0
    assert mint_batch(0, 0, ingest_ns=42).ingest_ns == 42


# -- emission + reconciliation ----------------------------------------------

def test_every_decision_reconciles_exactly(workload, tmp_path):
    d, dig, evs, _ = _run_traced(workload, tmp_path)
    decisions = _decisions(evs)
    assert len(decisions) == dig["windows_processed"] > 0
    assert dig["traced_decisions"] == len(decisions)
    for dec in decisions:
        # Integer equality, not approximation: the segments are deltas
        # of one perf_counter_ns clock and MUST telescope to the total.
        assert sum(dec["segments_ns"].values()) == dec["total_ns"]
        assert set(dec["segments_ns"]) == {"tail", "decide", "observe",
                                           "publish"}
        assert dec["trace"] == decision_trace_id(dec["window"])
        assert dec["epoch_id"] >= 1
        assert dec["plan_hash"]


def test_untraced_run_emits_no_trace_events(workload, tmp_path):
    manifest, events = workload
    ctl = ReplicationController(manifest, _cfg())
    d = StreamDaemon(ctl)
    log = tmp_path / "events.cdrsb"
    events.write_binary(str(log), manifest)
    dig = d.run(str(log))
    assert dig["traced_decisions"] == 0
    assert d.publisher.record_pins is False


def test_published_epoch_carries_trace_id(workload, tmp_path):
    d, _, evs, _ = _run_traced(workload, tmp_path)
    ep = d.publisher.pin()
    last = _decisions(evs)[-1]
    assert ep.trace_id == last["trace"]
    assert ep.epoch_id == last["epoch_id"]


def test_pin_recording_and_digest_neutrality(workload, tmp_path):
    d, _, _, _ = _run_traced(workload, tmp_path)
    assert d.publisher.record_pins is True
    before = dict(d.publisher.first_pins)
    d.digest()   # reporting must never register a serve-path pin
    assert d.publisher.first_pins == before
    ep = d.publisher.pin()
    assert ep.epoch_id in d.publisher.first_pins


def test_exemplar_cap_limits_embedded_span_trees(workload, tmp_path):
    d, _, evs, _ = _run_traced(
        workload, tmp_path, dcfg=DaemonConfig(trace_exemplars=2))
    decisions = _decisions(evs)
    with_spans = [dec for dec in decisions if dec.get("spans")]
    flagged = [dec for dec in decisions if dec.get("exemplar")]
    assert with_spans == flagged
    # The heap admits a decision when it beats the N-th slowest SO FAR,
    # so >= cap events may carry trees; the final top-N is what an
    # analyzer keeps.  At least the cap's worth must be present.
    assert len(flagged) >= 2
    tots = sorted((dec["total_ns"] for dec in decisions), reverse=True)
    kept = {dec["total_ns"] for dec in flagged}
    assert set(tots[:2]) <= kept


def test_exemplars_disabled(workload, tmp_path):
    _, _, evs, _ = _run_traced(
        workload, tmp_path, dcfg=DaemonConfig(trace_exemplars=0))
    assert all(not dec.get("exemplar") and "spans" not in dec
               for dec in _decisions(evs))


def test_trace_exemplars_validation():
    with pytest.raises(ValueError, match="trace_exemplars"):
        DaemonConfig(trace_exemplars=-1)


# -- span trees --------------------------------------------------------------

def test_build_span_tree_nests_and_sums(workload, tmp_path):
    _, _, evs, _ = _run_traced(workload, tmp_path)
    windows = {e["window"]: e for e in evs if e.get("kind") == "window"}
    dec = _decisions(evs)[0]
    tree = build_span_tree(dict(dec, spans=None), windows[dec["window"]])
    assert tree[0]["name"] == "decision"
    assert tree[0]["parent"] is None
    assert tree[0]["dur_ns"] == dec["total_ns"]
    seg_rows = [r for r in tree if r["parent"] == 0]
    assert sum(r["dur_ns"] for r in seg_rows) == dec["total_ns"]
    stages = [r for r in tree
              if str(r["name"]).startswith("controller.")]
    assert stages, "window record present -> decide must expand"
    decide_idx = next(i for i, r in enumerate(tree)
                      if r["name"] == "decide")
    assert all(r["parent"] == decide_idx for r in stages)


def test_embedded_exemplar_tree_matches_rebuild(workload, tmp_path):
    _, _, evs, _ = _run_traced(workload, tmp_path)
    windows = {e["window"]: e for e in evs if e.get("kind") == "window"}
    dec = next(d for d in _decisions(evs) if d.get("exemplar"))
    rebuilt = build_span_tree(dict(dec, spans=None),
                              windows[dec["window"]])
    assert dec["spans"] == rebuilt


# -- determinism: double run + kill/resume ----------------------------------

def test_canonical_export_byte_stable_across_double_run(workload,
                                                        tmp_path):
    _, _, evs1, _ = _run_traced(workload, tmp_path, name="m1.jsonl")
    _, _, evs2, _ = _run_traced(workload, tmp_path, name="m2.jsonl")
    t1 = json.dumps(chrome_trace(evs1, canonical=True), sort_keys=True)
    t2 = json.dumps(chrome_trace(evs2, canonical=True), sort_keys=True)
    assert t1 == t2
    # And the wall-clock fields really were the only difference.
    raw1 = chrome_trace(evs1)["traceEvents"]
    raw2 = chrome_trace(evs2)["traceEvents"]
    assert [(e.get("name"), e.get("cat")) for e in raw1] \
        == [(e.get("name"), e.get("cat")) for e in raw2]


def test_resume_keeps_trace_lineage(workload, tmp_path):
    manifest, events = workload
    log = tmp_path / "events.cdrsb"
    events.write_binary(str(log), manifest)
    metrics = tmp_path / "stitched.jsonl"
    ck = tmp_path / "daemon.npz"

    a = StreamDaemon(ReplicationController(manifest, _cfg()),
                     DaemonConfig(max_windows=2))
    a.run(str(log), metrics_path=str(metrics), checkpoint_path=str(ck))
    b = StreamDaemon(ReplicationController(manifest, _cfg()))
    b.run(str(log), metrics_path=str(metrics), checkpoint_path=str(ck))

    full = StreamDaemon(ReplicationController(manifest, _cfg()))
    fm = tmp_path / "full.jsonl"
    dig = full.run(str(log), metrics_path=str(fm))

    with open(metrics, encoding="utf-8") as f:
        stitched = [json.loads(line) for line in f]
    with open(fm, encoding="utf-8") as f:
        uncut = [json.loads(line) for line in f]
    s_dec = collect(stitched)["decisions"]
    f_dec = collect(uncut)["decisions"]
    # Same lineage: identical trace ids per window, full coverage, and
    # every decision still reconciles — no orphan spans from the kill.
    assert [d["trace"] for d in s_dec] == [d["trace"] for d in f_dec]
    assert len(s_dec) == dig["windows_processed"]
    assert all(sum(d["segments_ns"].values()) == d["total_ns"]
               for d in s_dec)
    # The stitched epoch ids continue the daemon-lifetime sequence.
    assert [d["epoch_id"] for d in s_dec] \
        == [d["epoch_id"] for d in f_dec]


# -- reservoir ---------------------------------------------------------------

def test_decision_reservoir_is_bounded():
    from cdrs_tpu.obs.telemetry import HIST_RAW_CAP

    d = StreamDaemon.__new__(StreamDaemon)
    d.decision_seconds = []
    d._dec_seen = 0
    d._dec_stride = 1
    n = HIST_RAW_CAP * 4 + 123
    for i in range(n):
        d._record_decision(float(i))
    assert len(d.decision_seconds) < HIST_RAW_CAP
    assert d._dec_seen == n
    assert d._dec_stride > 1
    # Uniform decimation: kept samples are every stride-th observation.
    assert d.decision_seconds == [float(i) for i in range(n)
                                  if i % d._dec_stride == 0]


# -- aggregation -------------------------------------------------------------

def test_critical_path_digest(workload, tmp_path):
    _, _, evs, _ = _run_traced(workload, tmp_path)
    agg = collect(evs)
    cp = critical_path_digest(agg["decisions"], agg["windows"])
    assert cp["reconciled"] and cp["reconcile_mismatches"] == 0
    assert cp["decisions"] == len(agg["decisions"])
    assert cp["total_p99_seconds"] >= cp["total_p50_seconds"] > 0
    # decide expands into controller stages when windows join; shares
    # are a partition of the total event-to-decision time.
    assert "decide" not in cp["stage_shares"]
    assert "fold" in cp["stage_shares"]
    assert abs(sum(cp["stage_shares"].values()) - 1.0) < 1e-9
    assert cp["exemplars"] and cp["exemplars"][0]["total_seconds"] \
        == max(e["total_seconds"] for e in cp["exemplars"])


def test_critical_path_digest_flags_mismatch():
    bad = [{"kind": "decision_trace", "window": 0, "trace": "d000000",
            "total_ns": 100, "segments_ns": {"tail": 10, "decide": 80},
            "epoch_id": 1}]
    cp = critical_path_digest(bad, [])
    assert not cp["reconciled"]
    assert cp["reconcile_mismatches"] == 1


def test_daemon_digest(workload, tmp_path):
    _, dig, evs, _ = _run_traced(workload, tmp_path)
    agg = collect(evs)
    dd = daemon_digest(agg["decisions"], agg["epoch_pins"])
    assert dd["decisions"] == dig["windows_processed"]
    assert dd["epochs_published"] == dig["epochs_published"]
    assert dd["event_to_decision_p99_seconds"] \
        >= dd["event_to_decision_p50_seconds"] > 0
    assert critical_path_digest([], []) is None
    assert daemon_digest([], []) is None


def test_epoch_pin_events_join_publish_provenance(workload, tmp_path):
    manifest, events = workload
    ctl = ReplicationController(manifest, _cfg())
    d = StreamDaemon(ctl)
    log = tmp_path / "events.cdrsb"
    events.write_binary(str(log), manifest)
    metrics = tmp_path / "pins.jsonl"

    # A serve-path reader pinning between decisions: observe the pin
    # through the follow-mode alert hook by pinning inside the loop via
    # record hook — simplest honest route: run once, pin, run a second
    # daemon resuming the SAME publisher is not supported, so instead
    # drive the loop manually with a feed and pin between windows.
    batches = [events]
    d.publisher.record_pins = True  # what a traced run() sets

    orig_publish = d._publish
    pinned = []

    def publish_and_pin(w, rec, trace_id=None):
        ep = orig_publish(w, rec, trace_id=trace_id)
        pinned.append(d.publisher.pin().epoch_id)   # reader pins
        return ep

    d._publish = publish_and_pin
    d.run(batches, metrics_path=str(metrics))
    with open(metrics, encoding="utf-8") as f:
        evs = [json.loads(line) for line in f]
    pins = [e for e in evs if e.get("kind") == "epoch_pin"]
    assert pins, "pinned epochs must surface as epoch_pin events"
    for p in pins:
        assert p["epoch_id"] in pinned
        assert p["trace"] == decision_trace_id(p["window"])
        assert p["publish_to_pin_ns"] >= 0
    dd = daemon_digest(collect(evs)["decisions"],
                       collect(evs)["epoch_pins"])
    assert dd["epochs_pinned"] == len(pins)


# -- CLI ---------------------------------------------------------------------

def test_trace_cli_list_show_export(workload, tmp_path, capsys):
    _, _, evs, metrics = _run_traced(workload, tmp_path)
    assert trace_mod.main(["list", str(metrics)]) == 0
    out = capsys.readouterr().out
    assert "trace" in out and "d0000" in out

    w = _decisions(evs)[0]["window"]
    assert trace_mod.main(["show", str(metrics), str(w)]) == 0
    out = capsys.readouterr().out
    assert "reconciled" in out
    assert "published epoch" in out
    assert "cdrs explain window" in out
    # trace-id addressing resolves to the same decision
    assert trace_mod.main(
        ["show", str(metrics), decision_trace_id(w)]) == 0

    dst = tmp_path / "chrome.json"
    assert trace_mod.main(["export", str(metrics),
                           "--out", str(dst)]) == 0
    doc = json.loads(dst.read_text())
    assert doc["displayTimeUnit"] == "ms"
    kinds = {e.get("cat") for e in doc["traceEvents"] if "cat" in e}
    assert {"decision", "segment"} <= kinds


def test_trace_cli_errors(tmp_path, workload):
    with pytest.raises(SystemExit):
        trace_mod.main(["list", str(tmp_path / "missing.jsonl")])
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(SystemExit):
        trace_mod.main(["list", str(empty)])
    # A stream with telemetry but no decisions names the daemon command.
    nodec = tmp_path / "nodec.jsonl"
    nodec.write_text('{"kind": "window", "window": 0}\n')
    with pytest.raises(SystemExit, match="cdrs daemon"):
        trace_mod.main(["list", str(nodec)])
    _, _, _, metrics = _run_traced(workload, tmp_path)
    with pytest.raises(SystemExit, match="no traced decision"):
        trace_mod.main(["show", str(metrics), "999"])


def test_cdrs_trace_subcommand_wired(workload, tmp_path, capsys):
    from cdrs_tpu.cli import main as cli_main

    _, _, _, metrics = _run_traced(workload, tmp_path)
    assert cli_main(["trace", "list", str(metrics)]) == 0
    assert "d0000" in capsys.readouterr().out


def test_summarize_renders_daemon_and_critical_path(workload, tmp_path):
    from cdrs_tpu.obs.metrics_cli import summarize_events

    _, _, evs, _ = _run_traced(workload, tmp_path)
    buf = io.StringIO()
    summarize_events(evs, out=buf)
    text = buf.getvalue()
    assert "Daemon:" in text
    assert "Critical path: decision p99" in text
    assert "reconciled" in text


def test_report_renders_critical_path_section(workload, tmp_path):
    from cdrs_tpu.obs.report import render_html

    _, _, evs, _ = _run_traced(workload, tmp_path)
    html = render_html(evs)
    assert "Decision critical path" in html
    assert "traced decisions" in html


def test_stage_latency_rules_present_and_silent_on_healthy_stream(
        workload, tmp_path):
    from cdrs_tpu.obs.alerts import (
        DEFAULT_RULE_NAMES,
        AlertEngine,
        default_rules,
    )

    assert {"stage_plan_latency", "decision_latency"} \
        <= set(DEFAULT_RULE_NAMES)
    _, _, evs, _ = _run_traced(workload, tmp_path)
    eng = AlertEngine(default_rules())
    for e in evs:
        if e.get("kind") == "window":
            eng.observe(e)
    fired = {r["name"] for r in eng.results() if r["fired"]}
    assert not ({"stage_plan_latency", "decision_latency"} & fired)
