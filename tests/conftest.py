"""Test configuration: force JAX onto an 8-device virtual CPU mesh.

The JAX analogue of the reference's docker-compose fake cluster (SURVEY.md §4):
multi-chip sharding is exercised on host CPU with
``--xla_force_host_platform_device_count=8``.

The ambient environment may register a real TPU backend at interpreter startup
(a sitecustomize driven by PALLAS_AXON_POOL_IPS sets jax_platforms to the TPU
plugin) — env vars alone are therefore too late here.  We override the config
directly and clear any initialized backends so tests always run on the virtual
CPU mesh; only bench.py uses the real chip.

jax is an optional dependency (the ``tpu`` extra): with no jax installed the
numpy-backend tests still run, and jax-dependent test modules are skipped at
collection via their own imports.
"""

import os

#: CDRS_TPU_TESTS=1 leaves the ambient (TPU) backend in place so the
#: tpu-marked modules (tests/test_tpu_chip.py) can run non-interpret kernels
#: on a real chip:  ``CDRS_TPU_TESTS=1 pytest tests/test_tpu_chip.py``.
#: Everything else in the suite assumes the 8-device CPU mesh — run the full
#: suite without this flag.
_TPU_MODE = os.environ.get("CDRS_TPU_TESTS") == "1"

if not _TPU_MODE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_ENABLE_X64", "1")

try:
    import jax
except ImportError:  # pragma: no cover - base install without the tpu extra
    jax = None

if jax is not None and not _TPU_MODE:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    try:  # private API; best-effort cleanup of site-hook-initialized backends
        from jax._src.xla_bridge import backends_are_initialized
        if backends_are_initialized():  # pragma: no cover - site-hook dependent
            from jax.extend.backend import clear_backends

            clear_backends()
    except ImportError:  # pragma: no cover
        pass
    n_dev = len(jax.devices())
    if n_dev < 8:  # pragma: no cover - foreign XLA_FLAGS already set a count
        import pytest

        pytest.exit(
            f"tests need 8 virtual CPU devices, got {n_dev} "
            f"(XLA_FLAGS={os.environ.get('XLA_FLAGS')!r})", returncode=3,
        )


import pytest  # noqa: E402


@pytest.fixture
def crash_fold_after(monkeypatch):
    """Install a streaming-fold crash injector: the fold raises after N
    successful batches.  Returns a restore() callable so the test can put
    the real fold back before resuming; teardown restores regardless."""

    def _install(n: int, msg: str = "simulated crash"):
        from cdrs_tpu.features import streaming as S

        real = S._fold_prepped
        calls = {"n": 0}

        def exploding(state, pb):
            calls["n"] += 1
            if calls["n"] > n:
                raise RuntimeError(msg)
            return real(state, pb)

        monkeypatch.setattr(S, "_fold_prepped", exploding)
        return lambda: monkeypatch.setattr(S, "_fold_prepped", real)

    return _install
