"""Test configuration: force JAX onto an 8-device virtual CPU mesh.

The JAX analogue of the reference's docker-compose fake cluster (SURVEY.md §4):
multi-chip sharding is exercised on host CPU with
``--xla_force_host_platform_device_count=8``.  Must be set before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
