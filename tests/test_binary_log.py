"""Binary columnar event log (.cdrsb — VERDICT r4 #2).

The CSV access.log stays the interchange contract; the binary sidecar is the
parse-free fast path for billion-event feeds.  These tests pin round-trip
fidelity against the CSV path, the auto-detect dispatch, append safety, and
streaming-fold parity (offsets included).
"""

import numpy as np
import pytest

from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.io.events import EventLog, Manifest, is_binary_log
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=120, seed=11))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=120.0, seed=12))
    return manifest, events


def _assert_logs_equal(a: EventLog, b: EventLog):
    np.testing.assert_array_equal(a.ts, b.ts)
    np.testing.assert_array_equal(a.path_id, b.path_id)
    np.testing.assert_array_equal(a.op, b.op)
    np.testing.assert_array_equal(a.client_id, b.client_id)
    assert a.clients == b.clients


def test_binary_round_trip_vs_csv(tmp_path, workload):
    """Binary write -> read returns exactly what the CSV path returns —
    except timestamps, where binary is BETTER (no ms truncation): compare
    CSV-read fields against binary-read fields after CSV-equal rounding."""
    manifest, events = workload
    csv_p, bin_p = str(tmp_path / "a.log"), str(tmp_path / "a.cdrsb")
    events.write_csv(csv_p, manifest)
    events.write_binary(bin_p, manifest)
    assert is_binary_log(bin_p) and not is_binary_log(csv_p)

    from_csv = EventLog.read_csv(csv_p, manifest)
    from_bin = EventLog.read_csv(bin_p, manifest)  # auto-dispatch
    np.testing.assert_array_equal(from_csv.path_id, from_bin.path_id)
    np.testing.assert_array_equal(from_csv.op, from_bin.op)
    np.testing.assert_array_equal(from_csv.client_id, from_bin.client_id)
    assert from_csv.clients == from_bin.clients
    # CSV truncates to ms; binary preserves the f64 exactly.
    np.testing.assert_array_equal(from_bin.ts, events.ts)
    np.testing.assert_allclose(from_csv.ts, from_bin.ts, atol=1e-3)


def test_binary_exact_event_log_round_trip(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "x.cdrsb")
    events.write_binary(p, manifest)
    back = EventLog.read_csv(p, manifest)
    _assert_logs_equal(events, back)


def test_binary_append_blocks(tmp_path, workload):
    """Chunked appends (the 1B-generator pattern) concatenate exactly."""
    manifest, events = workload
    p = str(tmp_path / "app.cdrsb")
    n = len(events)
    half = n // 2

    def slice_log(lo, hi):
        return EventLog(ts=events.ts[lo:hi], path_id=events.path_id[lo:hi],
                        op=events.op[lo:hi],
                        client_id=events.client_id[lo:hi],
                        clients=events.clients)

    slice_log(0, half).write_binary(p, manifest)
    slice_log(half, n).write_binary(p, manifest, append=True)
    back = EventLog.read_csv(p, manifest)
    _assert_logs_equal(events, back)


def test_binary_chunked_blocks_round_trip(tmp_path, workload):
    """One write_binary call split into many blocks (the 1B-writer layout):
    exact round trip incl. the partial final block, and every block
    boundary is a valid resume offset."""
    manifest, events = workload
    p = str(tmp_path / "chunked.cdrsb")
    n = len(events)
    block = 7  # forces many blocks + a partial final block (n % 7 != 0)
    assert n % block != 0
    events.write_binary(p, manifest, block_rows=block)
    back = EventLog.read_csv(p, manifest)
    _assert_logs_equal(events, back)

    # batch_size=block aligns batches with blocks: every batch ends a block
    # and must carry a resume offset that replays the exact remainder.
    got = list(EventLog.read_csv_batches(p, manifest, batch_size=block,
                                         with_offsets=True))
    assert sum(len(b) for b, _ in got) == n
    assert all(off is not None for _, off in got)
    rows = 0
    for b, off in got[:3]:
        rows += len(b)
        resumed = list(EventLog.read_csv_batches(p, manifest,
                                                 batch_size=None,
                                                 start_offset=off))
        np.testing.assert_array_equal(resumed[0].ts, events.ts[rows:])

    with pytest.raises(ValueError, match="block_rows"):
        events.write_binary(str(tmp_path / "bad.cdrsb"), manifest,
                            block_rows=0)


def test_binary_append_vocab_mismatch_raises(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "bad.cdrsb")
    events.write_binary(p, manifest)
    other = EventLog(ts=events.ts, path_id=events.path_id, op=events.op,
                     client_id=events.client_id,
                     clients=events.clients + ["intruder"])
    with pytest.raises(ValueError, match="vocabulary"):
        other.write_binary(p, manifest, append=True)


def test_binary_batches_and_offsets_resume(tmp_path, workload):
    """Batch slicing respects batch_size; a reported offset resumes to the
    identical remainder (the fold_stream checkpoint contract)."""
    manifest, events = workload
    p = str(tmp_path / "b.cdrsb")
    n = len(events)
    third = n // 3

    def slice_log(lo, hi):
        return EventLog(ts=events.ts[lo:hi], path_id=events.path_id[lo:hi],
                        op=events.op[lo:hi],
                        client_id=events.client_id[lo:hi],
                        clients=events.clients)

    slice_log(0, third).write_binary(p, manifest)
    slice_log(third, n).write_binary(p, manifest, append=True)

    got = list(EventLog.read_csv_batches(p, manifest, batch_size=100,
                                         with_offsets=True))
    assert sum(len(b) for b, _ in got) == n
    for b, _ in got[:-1]:
        assert len(b) <= 100
    # Offsets only at block boundaries; at least the final one is reported.
    offsets = [off for _, off in got if off is not None]
    assert offsets, "block-final batches must report a resume offset"

    # Resume from the first reported offset: remainder must be identical.
    rows_before = 0
    first_off = None
    for b, off in got:
        rows_before += len(b)
        if off is not None:
            first_off = off
            break
    resumed = list(EventLog.read_csv_batches(p, manifest, batch_size=None,
                                             start_offset=first_off))
    assert len(resumed) == 1
    np.testing.assert_array_equal(resumed[0].ts, events.ts[rows_before:])
    np.testing.assert_array_equal(resumed[0].path_id,
                                  events.path_id[rows_before:])


def test_binary_empty_log_and_empty_blocks(tmp_path, workload):
    """A 0-row log reads back empty (CSV parity); an empty appended block
    (the empty-final-flush pattern) is skipped, not a crash."""
    manifest, events = workload
    empty = EventLog(ts=np.zeros(0), path_id=np.zeros(0, np.int32),
                     op=np.zeros(0, np.int8),
                     client_id=np.zeros(0, np.int32),
                     clients=list(events.clients))
    p = str(tmp_path / "e.cdrsb")
    empty.write_binary(p, manifest)
    back = EventLog.read_csv(p, manifest)
    assert len(back) == 0

    p2 = str(tmp_path / "e2.cdrsb")
    events.write_binary(p2, manifest)
    empty.write_binary(p2, manifest, append=True)  # same vocab: legal
    back2 = EventLog.read_csv(p2, manifest)
    _assert_logs_equal(events, back2)


def test_binary_truncated_file_raises_clearly(tmp_path, workload):
    manifest, events = workload
    p = str(tmp_path / "t.cdrsb")
    events.write_binary(p, manifest)
    size = (tmp_path / "t.cdrsb").stat().st_size
    # Truncate inside the trailing cid column AND inside a count field.
    for cut in (size - 3, size - len(events) * (8 + 4 + 1 + 4) - 3):
        with open(p, "r+b") as f:
            f.truncate(cut)
        with pytest.raises(ValueError, match="truncated/corrupt block"):
            EventLog.read_csv(p, manifest)
        events.write_binary(p, manifest)  # restore


def test_binary_out_of_range_ids_raise(tmp_path, workload):
    """A corrupt block whose pid/cid is negative or past the embedded table
    must raise the corrupt-block ValueError — numpy negative indexing would
    otherwise wrap it through the LUT into silently wrong rows (ADVICE r5)."""
    manifest, events = workload
    p = str(tmp_path / "r.cdrsb")
    events.write_binary(p, manifest)
    with open(p, "rb") as f:
        _, _, first_block = EventLog._read_binary_header(f)
    bn = len(events)
    pid_col = first_block + 8 + 8 * bn          # [count][ts f64]...[pid i32]
    cid_col = pid_col + 4 * bn + bn             # ...[op i8][cid i32]
    for off, bad, msg in ((pid_col, -3, "path id"),
                          (pid_col, len(manifest.paths) + 7, "path id"),
                          (cid_col, -1, "client id"),
                          (cid_col, 10 ** 6, "client id")):
        with open(p, "r+b") as f:
            f.seek(off)
            orig = f.read(4)
            f.seek(off)
            f.write(np.int32(bad).tobytes())
        with pytest.raises(ValueError, match=msg):
            EventLog.read_csv(p, manifest)
        with open(p, "r+b") as f:        # restore
            f.seek(off)
            f.write(orig)
    _assert_logs_equal(events, EventLog.read_csv(p, manifest))


def test_binary_batches_none_is_one_batch(tmp_path, workload):
    """batch_size=None concatenates every block into ONE EventLog — the
    read_csv_batches whole-file contract, now honored by the public
    read_binary_batches classmethod itself."""
    manifest, events = workload
    p = str(tmp_path / "one.cdrsb")
    events.write_binary(p, manifest, block_rows=17)  # many small blocks
    got = list(EventLog.read_binary_batches(p, manifest, batch_size=None))
    assert len(got) == 1
    log, off = got[0]
    assert off is None
    _assert_logs_equal(events, log)


def test_binary_foreign_manifest_left_join(tmp_path, workload):
    """Reading with a manifest missing some paths maps them to -1 (the CSV
    reader's left-join semantics) and extends the client vocabulary."""
    manifest, events = workload
    p = str(tmp_path / "f.cdrsb")
    events.write_binary(p, manifest)

    import copy

    m2 = copy.deepcopy(manifest)
    # Drop the last 20 files from the reader's manifest.
    keep = len(manifest) - 20
    m2.paths = m2.paths[:keep]
    m2.creation_ts = m2.creation_ts[:keep]
    m2.primary_node_id = m2.primary_node_id[:keep]
    m2.size_bytes = m2.size_bytes[:keep]
    m2.category = m2.category[:keep]
    m2.path_to_id = {pp: i for i, pp in enumerate(m2.paths)}

    back = EventLog.read_csv(p, m2)
    dropped = events.path_id >= keep
    assert (back.path_id[dropped] == -1).all()
    np.testing.assert_array_equal(back.path_id[~dropped],
                                  events.path_id[~dropped])


def test_fold_stream_binary_csv_parity(tmp_path, workload):
    """The streaming feature fold over the binary log equals the CSV fold
    bit-for-bit once timestamps match (write CSV, read it back, binarize)."""
    from cdrs_tpu.features.streaming import fold_stream, stream_finalize

    manifest, events = workload
    csv_p, bin_p = str(tmp_path / "p.log"), str(tmp_path / "p.cdrsb")
    events.write_csv(csv_p, manifest)
    # Round timestamps through the CSV to make the two sources identical.
    ev_ms = EventLog.read_csv(csv_p, manifest)
    ev_ms.write_binary(bin_p, manifest)

    t_csv = stream_finalize(fold_stream(csv_p, manifest, batch_size=500),
                            manifest)
    t_bin = stream_finalize(fold_stream(bin_p, manifest, batch_size=500),
                            manifest)
    np.testing.assert_array_equal(np.asarray(t_csv.raw),
                                  np.asarray(t_bin.raw))


def test_cli_simulate_binary_format(tmp_path, capsys):
    from cdrs_tpu.cli import main

    mpath = tmp_path / "m.csv"
    manifest = generate_population(GeneratorConfig(n_files=40, seed=5))
    manifest.write_csv(str(mpath))
    out = tmp_path / "a.cdrsb"
    rc = main(["simulate", "--manifest", str(mpath), "--out", str(out),
               "--duration_seconds", "60", "--seed", "5"])
    assert rc == 0
    assert is_binary_log(str(out))  # --format auto picked binary by suffix
    ev = EventLog.read_csv(str(out), manifest)
    assert len(ev) > 0


# -- clean one-line reader errors (daemon round: operator-facing shapes) ----

def test_manifest_missing_truncated_corrupt_one_line_errors(tmp_path,
                                                            workload):
    """Each broken-manifest shape raises ONE clean error naming the path:
    missing file, truncated (no header), corrupt (unreadable row)."""
    manifest, _ = workload
    missing = str(tmp_path / "ghost.csv")
    with pytest.raises(FileNotFoundError, match="missing manifest") as ei:
        Manifest.read_csv(missing)
    assert "ghost.csv" in str(ei.value)

    empty = tmp_path / "empty.csv"
    empty.write_text("")
    with pytest.raises(ValueError, match="no header row") as ei:
        Manifest.read_csv(str(empty))
    assert "empty.csv" in str(ei.value)

    nocol = tmp_path / "nocol.csv"
    nocol.write_text("path,creation_ts\n/a,1.0\n")
    with pytest.raises(ValueError, match="missing columns"):
        Manifest.read_csv(str(nocol))

    good = tmp_path / "good.csv"
    manifest.write_csv(str(good))
    lines = good.read_text().splitlines()
    lines[2] = lines[2].replace(lines[2].split(",")[1], "not-a-stamp", 1)
    bad = tmp_path / "bad.csv"
    bad.write_text("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="truncated/corrupt manifest") as ei:
        Manifest.read_csv(str(bad))
    assert "row 3" in str(ei.value) and "bad.csv" in str(ei.value)


def test_binary_header_shapes_one_line_errors(tmp_path, workload):
    """Every torn/corrupt header shape names the path in one line: bad
    magic, a cut inside the vocabulary tables, a missing file."""
    manifest, events = workload
    p = str(tmp_path / "h.cdrsb")
    events.write_binary(p, manifest)
    with open(p, "rb") as f:
        blob = f.read()
        f.seek(0)
        _, _, first_block = EventLog._read_binary_header(f)

    with pytest.raises(FileNotFoundError, match="missing event log"):
        EventLog.read_csv(str(tmp_path / "none.cdrsb"), manifest)

    wrong = tmp_path / "magic.cdrsb"
    wrong.write_bytes(b"NOTMAGIC" + blob[8:])
    with pytest.raises(ValueError, match="bad magic"):
        list(EventLog.read_binary_batches(str(wrong), manifest))

    for cut in (4, 20, first_block - 3):  # mid-magic, mid-head, mid-table
        torn = tmp_path / "torn.cdrsb"
        torn.write_bytes(blob[:cut])
        with pytest.raises(ValueError,
                           match="truncated/corrupt header") as ei:
            list(EventLog.read_binary_batches(str(torn), manifest))
        assert "torn.cdrsb" in str(ei.value)
