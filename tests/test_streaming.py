"""Streaming subsystem tests.

Invariant: streaming any batch split of a time-ordered log must reproduce the
full-log features exactly (including seconds split across batch boundaries),
and mini-batch KMeans must recover planted blob structure.
"""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.features.numpy_backend import compute_features
from cdrs_tpu.features.streaming import stream_finalize, stream_init, stream_update
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.ops.kmeans_stream import MiniBatchKMeans
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=100, seed=3))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=90.0, seed=3))
    return manifest, events


def _slice_events(events, lo, hi):
    return EventLog(
        ts=events.ts[lo:hi], path_id=events.path_id[lo:hi],
        op=events.op[lo:hi], client_id=events.client_id[lo:hi],
        clients=events.clients,
    )


@pytest.mark.parametrize("n_batches", [1, 3, 7])
def test_stream_matches_batch_features(workload, n_batches):
    manifest, events = workload
    want = compute_features(manifest, events)

    state = stream_init(len(manifest))
    # Deliberately uneven splits (prime-ish offsets) to cut inside seconds.
    cuts = np.linspace(0, len(events), n_batches + 1).astype(int)
    cuts[1:-1] += 13  # shift interior cuts off any natural boundary
    cuts = np.clip(cuts, 0, len(events))
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        state = stream_update(state, _slice_events(events, int(lo), int(hi)), manifest)
    got = stream_finalize(state, manifest)

    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)


def test_stream_concurrency_boundary_merge(workload):
    """A (path, second) run split across batches must count as one run."""
    manifest, _ = workload
    n = len(manifest)
    base = 1_700_000_000.0
    # 6 events for file 0 in the same second, split 2/4 across batches.
    ts = np.array([base + 0.1, base + 0.2, base + 0.3, base + 0.4,
                   base + 0.5, base + 0.6])
    mk = lambda lo, hi: EventLog(
        ts=ts[lo:hi],
        path_id=np.zeros(hi - lo, dtype=np.int32),
        op=np.zeros(hi - lo, dtype=np.int8),
        client_id=np.zeros(hi - lo, dtype=np.int32),
        clients=["dn1"],
    )
    state = stream_init(n)
    state = stream_update(state, mk(0, 2), manifest)
    state = stream_update(state, mk(2, 6), manifest)
    got = stream_finalize(state, manifest)
    assert got.raw[0, 4] == 6.0  # concurrency: all six in one second


@pytest.mark.parametrize("ndata", [2, 8])
@pytest.mark.parametrize("n_batches", [1, 4])
def test_sharded_stream_matches_batch_features(workload, ndata, n_batches):
    """The mesh-sharded fold reproduces the full-log features exactly."""
    manifest, events = workload
    want = compute_features(manifest, events)

    state = stream_init(len(manifest))
    cuts = np.linspace(0, len(events), n_batches + 1).astype(int)
    cuts[1:-1] += 7
    cuts = np.clip(cuts, 0, len(events))
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        state = stream_update(state, _slice_events(events, int(lo), int(hi)),
                              manifest, mesh_shape={"data": ndata})
    got = stream_finalize(state, manifest)

    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)


def test_sharded_stream_hot_second_across_batches_and_shards(workload):
    """One second's events split across batches AND shards counts exactly once
    with the carry folded in."""
    manifest, _ = workload
    n = len(manifest)
    base = 1_700_000_000.0
    ts = base + np.linspace(0.0, 0.9, 19)  # 19 events, one second, file 0
    mk = lambda lo, hi: EventLog(
        ts=ts[lo:hi],
        path_id=np.zeros(hi - lo, dtype=np.int32),
        op=np.zeros(hi - lo, dtype=np.int8),
        client_id=np.zeros(hi - lo, dtype=np.int32),
        clients=["dn1"],
    )
    state = stream_init(n)
    state = stream_update(state, mk(0, 5), manifest, mesh_shape={"data": 4})
    state = stream_update(state, mk(5, 19), manifest, mesh_shape={"data": 4})
    got = stream_finalize(state, manifest)
    assert got.raw[0, 4] == 19.0


def test_minibatch_kmeans_recovers_blobs():
    rng = np.random.default_rng(5)
    centers = rng.normal(size=(8, 16)) * 5.0
    mb = MiniBatchKMeans(k=8, seed=1, mesh_shape={"data": 4})
    for _ in range(30):
        lab = rng.integers(0, 8, size=512)
        batch = centers[lab] + rng.normal(size=(512, 16)) * 0.3
        mb.partial_fit(batch.astype(np.float32))
    got = mb.centroids
    # Every true center must have a learned centroid within a small distance.
    d = np.linalg.norm(centers[:, None, :] - got[None, :, :], axis=2)
    assert d.min(axis=1).max() < 1.0
    # predict() assigns a fresh blob sample to the matching centroid
    lab = rng.integers(0, 8, size=256)
    X = centers[lab] + rng.normal(size=(256, 16)) * 0.3
    pred = mb.predict(X)
    # consistency: points from the same true blob map to the same centroid
    for j in range(8):
        p = pred[lab == j]
        assert (p == p[0]).mean() > 0.95


def test_minibatch_model_path_consistent_with_full_batch():
    """ReplicationPolicyModel with batch_size set must recover the same blob
    structure (and categories) as the full-batch path on small data."""
    from cdrs_tpu.config import KMeansConfig
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    rng = np.random.default_rng(11)
    centers = rng.random((4, 5))  # feature-space-like [0,1] blobs
    lab = rng.integers(0, 4, size=2000)
    X = np.clip(centers[lab] + rng.normal(size=(2000, 5)) * 0.03, 0, 1)

    full = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=4, seed=0), backend="jax").run(X)
    mini = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=4, seed=0, batch_size=256),
        backend="jax").run(X)

    # Same partition up to cluster relabeling: match mini centroids to full
    # centroids and compare label agreement + categories.
    d = np.linalg.norm(full.centroids[:, None] - mini.centroids[None], axis=2)
    perm = d.argmin(axis=1)
    assert sorted(perm.tolist()) == [0, 1, 2, 3]  # bijective matching
    agree = (perm[full.labels] == mini.labels).mean()
    assert agree > 0.98
    assert [mini.categories[perm[j]] for j in range(4)] == full.categories


def test_cli_stream_minibatch_and_numpy_fold(tmp_path, workload):
    """CLI-level: `cdrs stream --kmeans_batch N` (jax) and `--backend numpy`
    both produce a final_categories.csv consistent with the batch path."""
    from cdrs_tpu.cli import main

    manifest, events = workload
    mpath, apath = tmp_path / "m.csv", tmp_path / "a.log"
    manifest.write_csv(str(mpath))
    events.write_csv(str(apath), manifest)

    # batch reference via the pipeline stages
    out_batch = tmp_path / "batch.csv"
    rc = main(["features", "--manifest", str(mpath), "--access_log",
               str(apath), "--out", str(tmp_path / "f.csv")])
    assert rc == 0
    rc = main(["cluster", "--input_path", str(tmp_path / "f.csv"),
               "--k", "4", "--seed", "0", "--output_csv", str(out_batch),
               "--medians_from_data"])
    assert rc == 0

    out_mb = tmp_path / "mb.csv"
    rc = main(["stream", "--manifest", str(mpath), "--access_log", str(apath),
               "--batch_size", "512", "--k", "4", "--seed", "0",
               "--backend", "jax", "--kmeans_batch", "64",
               "--output_csv", str(out_mb), "--medians_from_data",
               "--checkpoint", str(tmp_path / "stream.ckpt.npz"),
               "--checkpoint_every", "2"])
    assert rc == 0
    assert not os.path.exists(tmp_path / "stream.ckpt.npz")  # consumed
    out_np = tmp_path / "np.csv"
    rc = main(["stream", "--manifest", str(mpath), "--access_log", str(apath),
               "--batch_size", "512", "--k", "4", "--seed", "0",
               "--backend", "numpy", "--output_csv", str(out_np),
               "--medians_from_data"])
    assert rc == 0

    import csv as _csv
    cats = {}
    for name, p in (("batch", out_batch), ("mb", out_mb), ("np", out_np)):
        with open(p) as f:
            rows = list(_csv.DictReader(f))
        assert len(rows) == 4
        for r in rows:
            assert r["category"] in ("Hot", "Shared", "Moderate", "Archival")
        cats[name] = sorted(r["category"] for r in rows)
    # numpy full-batch stream path matches the batch CLI path exactly (the
    # stream fold is bit-exact).  Mini-batch is a different algorithm on a
    # wall-clock-anchored workload, so only its structure is asserted here;
    # deterministic mini-batch-vs-full-batch consistency is covered by
    # test_minibatch_model_path_consistent_with_full_batch on planted blobs.
    assert cats["np"] == cats["batch"]


def test_minibatch_state_is_checkpointable():
    """State round-trips through host numpy (checkpoint/resume, SURVEY.md §5)."""
    import jax.numpy as jnp

    from cdrs_tpu.ops.kmeans_stream import MiniBatchState, minibatch_update

    rng = np.random.default_rng(0)
    mb = MiniBatchKMeans(k=4, seed=0)
    b1 = rng.normal(size=(128, 8)).astype(np.float32)
    b2 = rng.normal(size=(128, 8)).astype(np.float32)
    mb.partial_fit(b1)

    # checkpoint -> restore -> continue
    ckpt = (np.asarray(mb.state.centroids), np.asarray(mb.state.counts))
    restored = MiniBatchState(jnp.asarray(ckpt[0]), jnp.asarray(ckpt[1]),
                              n_batches=1)
    s2, _ = minibatch_update(restored, b2)
    mb.partial_fit(b2)
    np.testing.assert_allclose(np.asarray(mb.state.centroids),
                               np.asarray(s2.centroids), atol=1e-6)


def test_variable_tail_batches_single_compile():
    """A shorter tail batch bucket-pads up to the full batch size and reuses
    the SAME compiled fold — exactly one _build_update compilation for the
    whole stream (VERDICT r2 weak #6) — while staying bit-exact with the
    batch backend."""
    from cdrs_tpu.features import streaming as S

    manifest = generate_population(GeneratorConfig(n_files=40, seed=31))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=120.0,
                                                       seed=32))
    n = len(manifest)
    want = compute_features(manifest, events)

    S._build_update.cache_clear()
    st = S.stream_init(n)
    e = len(events)
    assert e % 1000 != 0, "workload should produce a ragged tail"
    for lo in range(0, e, 1000):
        st = S.stream_update(st, _slice_events(events, lo, min(lo + 1000, e)),
                             manifest)
    info = S._build_update.cache_info()
    assert info.misses == 1, f"expected one compiled fold, got {info.misses}"

    table = S.stream_finalize(st, manifest)
    np.testing.assert_allclose(np.asarray(table.raw), want.raw, atol=1e-9)


def test_fold_stream_matches_batch(tmp_path, workload):
    """The pipelined driver (prefetch thread) is feature-exact vs the batch
    backend, from a real on-disk log."""
    from cdrs_tpu.features.streaming import fold_stream

    manifest, events = workload
    log = str(tmp_path / "access.log")
    events.write_csv(log, manifest)
    # Golden from the RE-READ log (on-disk timestamps are ms-truncated, so
    # age differs sub-ms from the in-memory events).
    want = compute_features(manifest, EventLog.read_csv(log, manifest))

    stats = {}
    state = fold_stream(log, manifest, batch_size=997, stats=stats)
    got = stream_finalize(state, manifest)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)
    assert stats["batches"] == -(-len(events) // 997)
    assert stats["producer_seconds"] > 0 and stats["fold_seconds"] > 0


def test_fold_stream_sharded_and_iterable_source(workload):
    """fold_stream over an iterable of batches on the 8-device mesh matches
    the batch features; producer exceptions surface in the caller."""
    from cdrs_tpu.features.streaming import fold_stream

    manifest, events = workload
    want = compute_features(manifest, events)
    cuts = np.linspace(0, len(events), 4).astype(int)
    batches = [_slice_events(events, int(lo), int(hi))
               for lo, hi in zip(cuts[:-1], cuts[1:])]
    state = fold_stream(batches, manifest, mesh_shape={"data": 4})
    got = stream_finalize(state, manifest)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)

    def bad_batches():
        yield batches[0]
        raise RuntimeError("boom in the parser thread")

    with pytest.raises(RuntimeError, match="boom in the parser"):
        fold_stream(bad_batches(), manifest)


def test_fold_stream_checkpoint_resume_bit_identical(tmp_path, workload,
                                                     crash_fold_after):
    """A fold killed mid-stream resumes from the checkpoint's byte offset and
    produces the SAME state as an uninterrupted fold (including the cross-
    batch concurrency carry); the checkpoint is deleted on completion.
    Checkpoint offsets exist only on the native parse path."""
    from cdrs_tpu.runtime.native import native_available

    if not native_available():
        pytest.skip("checkpoint offsets need the native parser")
    from cdrs_tpu.features import streaming as S

    manifest, events = workload
    log = str(tmp_path / "access.log")
    events.write_csv(log, manifest)
    ckpt = str(tmp_path / "stream.ckpt.npz")

    golden = S.fold_stream(log, manifest, batch_size=500)
    want = stream_finalize(golden, manifest)

    # Crash after the 4th fold (checkpoints every 2 batches -> the last
    # snapshot covers batch 4; batches 5+ were never folded).
    restore = crash_fold_after(4)
    with pytest.raises(RuntimeError, match="simulated crash"):
        S.fold_stream(log, manifest, batch_size=500,
                      checkpoint_path=ckpt, checkpoint_every=2)
    restore()
    assert os.path.exists(ckpt)

    # A stale checkpoint against a different manifest is a loud error.
    m2 = generate_population(GeneratorConfig(n_files=50, seed=4))
    with pytest.raises(ValueError, match="stale"):
        S.fold_stream(log, m2, batch_size=500, checkpoint_path=ckpt)

    stats = {}
    resumed = S.fold_stream(log, manifest, batch_size=500,
                            checkpoint_path=ckpt, checkpoint_every=2,
                            stats=stats)
    assert stats["resumed_from_offset"] > 0
    assert not os.path.exists(ckpt)   # consumed on success
    got = stream_finalize(resumed, manifest)
    assert resumed.n_events == golden.n_events == len(events)
    np.testing.assert_array_equal(np.asarray(got.raw), np.asarray(want.raw))


def test_wire_format_fallbacks_match(workload):
    """Unsorted batches and second-gaps > 255 must route to the "cols" wire
    format (the packed 5 B/event encoding requires monotone uint8 deltas)
    and stay feature-exact; sorted batches take "packed"."""
    from cdrs_tpu.features import streaming as S

    manifest, events = workload
    want = compute_features(manifest, events)

    # Shuffled within-batch order is legal on one device (the kernel
    # lexsorts); the negative deltas force wire="cols".
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(events))
    shuffled = EventLog(ts=events.ts[perm], path_id=events.path_id[perm],
                        op=events.op[perm], client_id=events.client_id[perm],
                        clients=events.clients)
    pb = S._prep_batch(shuffled, manifest, sec_base=None, pad_target=0)
    assert pb.wire == "cols"
    state = stream_update(stream_init(len(manifest)), shuffled, manifest)
    got = stream_finalize(state, manifest, observation_end=float(events.ts.max()))
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)

    # A sorted stream with a > 255 s silence also falls back...
    gap = EventLog(
        ts=np.array([1.7e9, 1.7e9 + 1.0, 1.7e9 + 1000.0]),
        path_id=np.zeros(3, np.int32), op=np.zeros(3, np.int8),
        client_id=np.zeros(3, np.int32), clients=["dn1"])
    pb = S._prep_batch(gap, manifest, sec_base=None, pad_target=0)
    assert pb.wire == "cols"
    # ...while the sorted workload log packs to 5 B/event.
    pb = S._prep_batch(events, manifest, sec_base=None, pad_target=0)
    assert pb.wire == "packed" and pb.sec.dtype == np.uint8


def test_stream1b_path_small_scale_matches_batch(tmp_path):
    """The full simulate -> native write -> native ingest -> device fold
    pipeline (benchmarks/stream1b) produces the same features as the batch
    backend at a small scale."""
    from cdrs_tpu.benchmarks.stream1b import run_stream1b
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.features.streaming import (stream_finalize, stream_init,
                                             stream_update)
    from cdrs_tpu.io.events import EventLog
    from cdrs_tpu.sim.generator import generate_population

    out = run_stream1b(events=50_000, n_files=500, batch_size=7_000,
                       seed=11, workdir=str(tmp_path), keep_log=True)
    assert out["feature_rows"] == 500
    assert out["events_simulated"] > 10_000

    # Re-derive features from the written log with the batch numpy backend.
    manifest = generate_population(GeneratorConfig(n_files=500, seed=11))
    log = str(tmp_path / "access.log")
    ev = EventLog.read_csv(log, manifest)
    golden = compute_features(manifest, ev)

    st = stream_init(500)
    for b in EventLog.read_csv_batches(log, manifest, batch_size=7_000):
        st = stream_update(st, b, manifest)
    table = stream_finalize(st, manifest)
    np.testing.assert_allclose(np.asarray(table.raw), golden.raw, atol=1e-9)
