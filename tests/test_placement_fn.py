"""Functional placement (cdrs_tpu/placement_fn): property tests +
functional-vs-materialized equivalence.

``CDRS_CHAOS_SEED`` varies the workloads below — CI sweeps it over 0/1/2
so the equivalence claims (flat bit-for-bit degeneration, subset == full,
controller decision identity, sparse-checkpoint kill/resume
bit-identity) are checked against three genuinely different populations,
not one lucky seed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, place_replicas
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.faults import FaultEvent, FaultSchedule
from cdrs_tpu.placement_fn import (
    EpochMap,
    FunctionalClusterState,
    compute_placement,
    primary_on_topology,
)
from cdrs_tpu.placement_fn.compute import (
    file_keys,
    hash_priorities,
    node_salts,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))

_NODES6 = tuple(f"dn{i}" for i in range(1, 7))
_RACKS6 = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6"


def _population(n=400, nodes=_NODES6):
    return generate_population(
        GeneratorConfig(n_files=n, seed=14 + SEED, nodes=nodes))


def _rand_inputs(n=2000, n_nodes=6, rf_hi=5):
    rng = np.random.default_rng(100 + SEED)
    return (np.arange(n, dtype=np.int64),
            rng.integers(1, rf_hi, n).astype(np.int32),
            rng.integers(0, n_nodes, n).astype(np.int32))


# -- chooser properties ------------------------------------------------------

def test_flat_degenerates_bitforbit_to_priority_policy():
    """Flat topology == the legacy distinct-node policy over the hash
    priorities: an INDEPENDENT argsort reference (the legacy chooser's
    order-by-key construction) must reproduce the chooser exactly."""
    fids, rf, prim = _rand_inputs()
    flat = ClusterTopology(_NODES6)
    slots, rfc = compute_placement(fids, rf, prim, flat, SEED)
    prio = hash_priorities(file_keys(fids, SEED),
                           node_salts(_NODES6, SEED)).T.astype(np.int64)
    key = prio.copy()
    key[np.arange(len(fids)), prim] = -1          # replica 0: the primary
    order = np.argsort(key, axis=1).astype(np.int32)
    ref = order[:, :slots.shape[1]].copy()
    ref[np.arange(slots.shape[1])[None, :] >= rfc[:, None]] = -1
    assert np.array_equal(slots, ref)


def test_subset_equals_full_rows():
    fids, rf, prim = _rand_inputs()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    full, _ = compute_placement(fids, rf, prim, topo, SEED)
    rng = np.random.default_rng(SEED)
    sub = rng.choice(len(fids), 137, replace=False)
    rows, _ = compute_placement(fids[sub], rf[sub], prim[sub], topo,
                                SEED, out_width=full.shape[1])
    assert np.array_equal(rows, full[sub])


def test_chunk_size_invariance():
    fids, rf, prim = _rand_inputs()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    a, _ = compute_placement(fids, rf, prim, topo, SEED)
    b, _ = compute_placement(fids, rf, prim, topo, SEED, chunk=173)
    assert np.array_equal(a, b)


def test_place_replicas_hash_is_the_materialized_twin():
    """place_replicas(method='hash') output == compute_placement over the
    full population (one policy, two surfaces)."""
    man = _population()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    rng = np.random.default_rng(SEED)
    rf = rng.integers(1, 5, len(man)).astype(np.int32)
    pr = place_replicas(man, rf, topo, seed=SEED, method="hash")
    prim = primary_on_topology(man.nodes, man.primary_node_id, topo)
    slots, rfc = compute_placement(np.arange(len(man)), rf, prim, topo,
                                   SEED)
    assert np.array_equal(pr.replica_map, slots)
    assert np.array_equal(pr.rf, rfc)


def test_domain_spread_invariant():
    """Replica 0 and 1 never share a failure domain when another domain
    exists, replicas 1 and 2 share the remote domain when it has two
    members (the HDFS rack-aware shape), and every row is distinct
    nodes."""
    fids, rf, prim = _rand_inputs(rf_hi=6)
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    slots, _ = compute_placement(fids, rf, prim, topo, SEED)
    dom = topo.domain_index()
    for i in range(len(fids)):
        row = slots[i][slots[i] >= 0]
        assert len(set(row.tolist())) == len(row)
        assert row[0] == prim[i]
        if len(row) >= 2:
            assert dom[row[0]] != dom[row[1]]
        if len(row) >= 3:
            assert dom[row[1]] == dom[row[2]]


def test_nested_in_rf():
    fids, rf, prim = _rand_inputs()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    hi, _ = compute_placement(fids, rf, prim, topo, SEED)
    lo, lo_rf = compute_placement(fids, np.maximum(rf - 1, 1), prim,
                                  topo, SEED)
    for i in range(len(fids)):
        k = int(lo_rf[i])
        assert np.array_equal(lo[i][:k], hi[i][:k])


def test_balance_is_uniform():
    """No node systematically over-draws: max/mean replica count within
    a few percent at 200k files (the straw2 uniformity property)."""
    fids, _, prim = _rand_inputs(n=200_000, n_nodes=12)
    topo = ClusterTopology(tuple(f"dn{i}" for i in range(1, 13)))
    slots, _ = compute_placement(
        fids, np.full(len(fids), 3, dtype=np.int32), prim, topo, SEED)
    counts = np.bincount(slots[slots >= 0], minlength=12)
    assert counts.max() / counts.mean() < 1.05


def test_determinism_across_processes_and_seeds():
    """Seeds 0/1/2 give stable, distinct placements, and a fresh
    interpreter reproduces the exact bytes (no salted-hash leakage)."""
    fids, rf, prim = _rand_inputs(n=500)
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    digests = []
    for seed in (0, 1, 2):
        a, _ = compute_placement(fids, rf, prim, topo, seed)
        b, _ = compute_placement(fids, rf, prim, topo, seed)
        assert np.array_equal(a, b)
        digests.append(a.tobytes())
    assert len({d for d in digests}) == 3
    script = (
        "import numpy as np\n"
        "from cdrs_tpu.cluster import ClusterTopology\n"
        "from cdrs_tpu.placement_fn import compute_placement\n"
        f"rng = np.random.default_rng({100 + SEED})\n"
        "n = 500\n"
        "fids = np.arange(n, dtype=np.int64)\n"
        "rf = rng.integers(1, 5, n).astype(np.int32)\n"
        "prim = rng.integers(0, 6, n).astype(np.int32)\n"
        f"topo = ClusterTopology.from_rack_spec({_NODES6!r}, "
        f"{_RACKS6!r})\n"
        "slots, _ = compute_placement(fids, rf, prim, topo, 0)\n"
        "import hashlib, sys\n"
        "sys.stdout.write(hashlib.blake2b(slots.tobytes(), "
        "digest_size=8).hexdigest())\n")
    got = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.dirname(__file__)),
                         check=True).stdout.strip()
    import hashlib

    rng2 = np.random.default_rng(100 + SEED)
    rf2 = rng2.integers(1, 5, 500).astype(np.int32)
    prim2 = rng2.integers(0, 6, 500).astype(np.int32)
    a, _ = compute_placement(np.arange(500, dtype=np.int64), rf2, prim2,
                             topo, 0)
    assert got == hashlib.blake2b(a.tobytes(), digest_size=8).hexdigest()


# -- epoch diff --------------------------------------------------------------

def test_epoch_diff_minimality_and_prune():
    man = _population(n=3000)
    rng = np.random.default_rng(SEED)
    shards = rng.integers(1, 5, len(man)).astype(np.int32)
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    emap = EpochMap(man.nodes, topo, seed=0)
    # Unchanged topology => zero moves, by construction.
    assert len(emap.diff(0, 0, shards, man.primary_node_id)) == 0
    emap.advance(ClusterTopology(topo.nodes, topo.domains))
    assert len(emap.diff(0, 1, shards, man.primary_node_id)) == 0
    # Remove one node: moved == full recompute compare, and every moved
    # file's OLD slots involve the removed node (nobody else re-rolls).
    survivors = tuple(x for x in _NODES6 if x != "dn4")
    emap.advance(ClusterTopology.from_rack_spec(
        survivors, "r0=dn1,dn2;r1=dn3;r2=dn5,dn6"))
    pruned = emap.diff(0, 2, shards, man.primary_node_id)
    full = emap.diff(0, 2, shards, man.primary_node_id, prune=False)
    assert pruned.pruned and not full.pruned
    assert np.array_equal(np.sort(pruned.moved), np.sort(full.moved))
    removed_idx = list(topo.nodes).index("dn4")
    old_all, _ = emap.placement(0, np.arange(len(man)), shards,
                                man.primary_node_id)
    holders = np.flatnonzero((old_all == removed_idx).any(axis=1))
    assert set(pruned.moved.tolist()) <= set(holders.tolist())
    # Untouched files keep identical rows across the epochs.
    untouched = np.setdiff1d(np.arange(len(man)), holders)
    new_rows, _ = emap.placement(2, untouched, shards[untouched],
                                 man.primary_node_id[untouched],
                                 out_width=old_all.shape[1])
    # Compare as node-NAME sets (ids differ across epochs).
    for i, f in enumerate(untouched[:200]):
        old_names = {topo.nodes[x] for x in old_all[f] if x >= 0}
        new_names = {survivors[x] for x in new_rows[i] if x >= 0}
        assert old_names == new_names


# -- functional cluster state ------------------------------------------------

def _fn_state(man, topo, rf, sparse=True):
    placement = place_replicas(man, rf, topo, seed=0, method="hash")
    return FunctionalClusterState(
        placement, np.asarray(man.size_bytes, dtype=np.int64),
        primary=primary_on_topology(man.nodes, man.primary_node_id,
                                    topo),
        seed=0, sparse_checkpoint=sparse)


def test_functional_state_sparse_roundtrip():
    """A fault-damaged functional state round-trips through the sparse
    snapshot bit-identically (map, corruption, strategy, caches)."""
    man = _population()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    rng = np.random.default_rng(SEED)
    rf = rng.integers(2, 4, len(man)).astype(np.int32)
    state = _fn_state(man, topo, rf)
    # Damage: crash, rf retargets (fast path), repairs, corruption.
    state.apply_event(FaultEvent(0, "crash", "dn3"))
    for f in rng.integers(0, len(man), 40):
        state.apply_rf_target(int(f), int(rng.integers(1, 5)))
    state.apply_event(FaultEvent(1, "corrupt", "dn2", fail_prob=0.3))
    arrays = state.state_arrays(rf_hint=rf)
    assert "fault_fn_sparse" in arrays
    arrays["current_rf"] = rf  # the controller checkpoint carries it
    fresh = _fn_state(man, topo, rf)
    fresh.load_state_arrays(arrays)
    for attr in ("replica_map", "slot_corrupt", "min_live",
                 "shard_bytes", "ec_k", "installed_shards", "node_up",
                 "node_bytes", "_live_counts", "_reach_counts",
                 "_dom_spread"):
        assert np.array_equal(getattr(fresh, attr),
                              getattr(state, attr)), attr
    assert fresh._n_corrupt == state._n_corrupt


def test_healthy_retargets_stay_in_base_form():
    """On a healthy cluster every rf migration rides the computed slot
    order — zero exceptions, which is the O(exceptions) checkpoint."""
    man = _population()
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    rng = np.random.default_rng(SEED)
    rf = rng.integers(2, 4, len(man)).astype(np.int32)
    state = _fn_state(man, topo, rf)
    for f in rng.integers(0, len(man), 100):
        state.apply_rf_target(int(f), int(rng.integers(1, 5)))
    assert state.exception_fids().size == 0


# -- controller equivalence --------------------------------------------------

def _controller_result(man, events, sizes, topo, mode, serve=True,
                       ck=None, maxw=None):
    from cdrs_tpu.serve import ServeConfig

    cfg = ControllerConfig(
        window_seconds=120.0, default_rf=2, drift_threshold=0.02,
        max_bytes_per_window=int(sizes.sum() * 0.25),
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(),
        topology=ClusterTopology(topo.nodes, topo.domains),
        fault_schedule=FaultSchedule(
            FaultSchedule.from_specs(["crash:dn3@3-6"])),
        placement_mode=mode,
        serve=ServeConfig(policy="p2c") if serve else None)
    ctl = ReplicationController(man, cfg)
    return ctl.run(events, checkpoint_path=ck, max_windows=maxw)


def _strip(records, drop=("seconds", "placement")):
    return [{k: v for k, v in r.items() if k not in drop}
            for r in records]


@pytest.fixture(scope="module")
def chaos_world():
    man = _population()
    events = simulate_access(
        man, SimulatorConfig(duration_seconds=1200.0, seed=15 + SEED))
    sizes = np.asarray(man.size_bytes, dtype=np.int64)
    topo = ClusterTopology.from_rack_spec(_NODES6, _RACKS6)
    return man, events, sizes, topo


def test_functional_decision_identical_to_materialized_oracle(
        chaos_world):
    """The acceptance contract: durability tiers, repair admissions,
    plan hashes and serve locality identical between the functional
    representation and the materialized oracle of the same policy."""
    man, events, sizes, topo = chaos_world
    fn = _controller_result(man, events, sizes, topo, "functional")
    orc = _controller_result(man, events, sizes, topo,
                             "materialized_hash")
    assert _strip(fn.records) == _strip(orc.records)
    assert np.array_equal(fn.rf, orc.rf)
    assert np.array_equal(fn.category_idx, orc.category_idx)
    # The engagement stamp: functional runs say so on every record.
    assert all(r["placement"]["mode"] == "functional"
               for r in fn.records)
    assert all("exceptions" in r["placement"] for r in fn.records)


def test_functional_kill_resume_bit_identity(chaos_world):
    """Mid-fault kill/resume through the SPARSE snapshot reproduces the
    uninterrupted run bit-for-bit — exceptions included (the stamped
    count is part of the compared records)."""
    man, events, sizes, topo = chaos_world
    ref = _controller_result(man, events, sizes, topo, "functional")
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "c.npz")
        a = _controller_result(man, events, sizes, topo, "functional",
                               ck=ck, maxw=4)
        b = _controller_result(man, events, sizes, topo, "functional",
                               ck=ck)
    strip_t = lambda r: _strip(r, drop=("seconds",))  # noqa: E731
    assert strip_t(a.records) + strip_t(b.records) == strip_t(
        ref.records)
    assert np.array_equal(b.rf, ref.rf)
    assert a.checkpoints and a.checkpoints[-1]["bytes"] > 0


def test_mode_mismatch_checkpoint_refused(chaos_world):
    man, events, sizes, topo = chaos_world
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "c.npz")
        _controller_result(man, events, sizes, topo, "functional",
                           ck=ck, maxw=2)
        with pytest.raises(ValueError, match="placement"):
            _controller_result(man, events, sizes, topo,
                               "materialized_hash", ck=ck)


def test_functional_serve_static_matches_oracle():
    """No-fault serve: the O(unique pids) resolver routes bit-identically
    to the materialized full map (locality, percentiles, everything)."""
    man = _population()
    events = simulate_access(
        man, SimulatorConfig(duration_seconds=600.0, seed=21 + SEED))
    sizes = np.asarray(man.size_bytes, dtype=np.int64)
    from cdrs_tpu.serve import ServeConfig

    def run(mode):
        cfg = ControllerConfig(
            window_seconds=120.0, default_rf=2,
            kmeans=KMeansConfig(k=8, seed=42),
            scoring=validated_scoring_config(),
            placement_mode=mode, serve=ServeConfig(policy="p2c"))
        return ReplicationController(man, cfg).run(events)

    fn, orc = run("functional"), run("materialized_hash")
    assert _strip(fn.records) == _strip(orc.records)


# -- checkpoint gauges (utils/checkpoint satellite) --------------------------

def test_save_state_returns_stats_and_emits_gauges(tmp_path):
    from cdrs_tpu.obs import JsonlSink, Telemetry
    from cdrs_tpu.utils.checkpoint import save_state

    out = tmp_path / "tele.jsonl"
    with Telemetry(JsonlSink(str(out))) as tel:  # noqa: F841
        stats = save_state(str(tmp_path / "x.npz"),
                           {"a": np.arange(10)}, {"k": 1})
    assert stats["bytes"] > 0 and stats["seconds"] >= 0
    text = out.read_text()
    assert "checkpoint.bytes" in text
    assert "checkpoint.save_seconds" in text
    import io

    from cdrs_tpu.obs.metrics_cli import main as metrics_main
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        metrics_main(["summarize", str(out)])
    assert "Checkpoint:" in buf.getvalue()
