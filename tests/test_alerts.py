"""Streaming alerting (obs/alerts.py) + its surfaces.

Covers: rule validation and JSON round trip, threshold streak
fire/resolve semantics, the SRE burn-rate pair, absence staleness (batch
and follow), the ``cdrs metrics alerts`` CLI (batch timeline, exit
codes, --follow), the watch dashboard's firing/resolved lines across
incremental reads, the Prometheus ``ALERTS`` export, the summarize and
HTML-report alert sections, JSONL sink rotation, and the metrics CLI's
clean-error contract on missing/empty/corrupt streams.
"""

import io
import json
import os
import threading
import time

import pytest

from cdrs_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    DEFAULT_RULE_NAMES,
    default_rules,
    evaluate_records,
    rules_from_json,
)
from cdrs_tpu.obs.metrics_cli import (
    main as metrics_main,
    prometheus_lines,
    summarize_events,
    watch,
)
from cdrs_tpu.obs.sink import JsonlSink, iter_events, read_events


def _win(w, **kw):
    return {"kind": "window", "window": w, "n_events": 10, **kw}


# -- rule validation ---------------------------------------------------------

def test_rule_validation_errors():
    with pytest.raises(ValueError, match="unknown kind"):
        AlertRule("x", kind="nope")
    with pytest.raises(ValueError, match="need a field"):
        AlertRule("x", kind="threshold")
    with pytest.raises(ValueError, match="unknown op"):
        AlertRule("x", field="a", op="!!")
    with pytest.raises(ValueError, match="short_windows"):
        AlertRule("x", kind="burn_rate", short_windows=4, long_windows=2)
    with pytest.raises(ValueError, match="severity"):
        AlertRule("x", field="a", severity="meh")


def test_rules_from_json_roundtrip_and_errors():
    rules = default_rules()
    back = rules_from_json(json.dumps([r.to_dict() for r in rules]))
    assert back == rules
    with pytest.raises(ValueError, match="unknown keys"):
        rules_from_json('[{"name": "x", "field": "a", "bogus": 1}]')
    with pytest.raises(ValueError, match="duplicate"):
        rules_from_json('[{"name": "x", "field": "a"},'
                        ' {"name": "x", "field": "b"}]')
    with pytest.raises(ValueError, match="must be a list"):
        rules_from_json('{"name": "x"}')


# -- threshold semantics -----------------------------------------------------

def test_threshold_fire_and_resolve_with_streak():
    rule = AlertRule("deg", field="durability.lost", for_windows=2)
    eng = AlertEngine([rule])
    assert eng.observe(_win(0, durability={"lost": 1})) == []  # streak 1
    t = eng.observe(_win(1, durability={"lost": 2}))
    assert [x["state"] for x in t] == ["firing"]
    assert t[0]["value"] == 2
    assert eng.observe(_win(2, durability={"lost": 3})) == []  # stays firing
    t = eng.observe(_win(3, durability={"lost": 0}))
    assert [x["state"] for x in t] == ["resolved"]
    res = eng.results()[0]
    assert res["fired"] and not res["firing"]
    assert [x["window"] for x in res["transitions"]] == [1, 3]


def test_threshold_missing_field_is_not_a_match():
    rule = AlertRule("deg", field="durability.lost")
    eng = AlertEngine([rule])
    # no durability key at all: never fires, never errors
    for w in range(3):
        assert eng.observe(_win(w)) == []
    assert not eng.results()[0]["fired"]


def test_threshold_summed_fields_and_bool():
    rule = AlertRule("any", field=("a.x", "a.y"))
    eng = AlertEngine([rule])
    assert eng.observe(_win(0, a={"x": 0, "y": 0})) == []
    assert [t["state"] for t in eng.observe(_win(1, a={"x": 0, "y": 2}))] \
        == ["firing"]
    scrub = AlertRule("sc", field="scrub.starved")
    e2 = AlertEngine([scrub])
    assert [t["state"] for t in e2.observe(
        _win(0, scrub={"starved": True}))] == ["firing"]


# -- burn rate ---------------------------------------------------------------

def test_burn_rate_pair_fires_and_resolves():
    rule = AlertRule("burn", kind="burn_rate", field="slo_burn",
                     short_windows=1, long_windows=3, factor=2.0)
    eng = AlertEngine([rule])
    # a spike BEFORE the long window has history must not page: the
    # anti-spike guard needs real history to mean anything
    assert eng.observe(_win(0, slo_burn=9.0)) == []
    assert eng.observe(_win(1, slo_burn=0.1)) == []
    # history full: short (last 1) >= 2 and long mean (9+0.1+9)/3 >= 2
    t = eng.observe(_win(2, slo_burn=9.0))
    assert [x["state"] for x in t] == ["firing"]
    # short window drops under the factor -> resolves
    t = eng.observe(_win(3, slo_burn=0.5))
    assert [x["state"] for x in t] == ["resolved"]


def test_burn_rate_long_window_guards_single_spike():
    # long=3 mean must ALSO clear the factor: one spike after a long
    # quiet stretch must not page.
    rule = AlertRule("burn", kind="burn_rate", field="slo_burn",
                     short_windows=1, long_windows=3, factor=2.0)
    eng = AlertEngine([rule])
    for w in range(3):
        assert eng.observe(_win(w, slo_burn=0.0)) == []
    assert eng.observe(_win(3, slo_burn=4.0)) == []  # long mean 4/3 < 2
    assert not eng.results()[0]["fired"]


def test_burn_rate_skips_serve_less_windows():
    rule = AlertRule("burn", kind="burn_rate", field="slo_burn",
                     short_windows=1, long_windows=2, factor=1.0)
    eng = AlertEngine([rule])
    eng.observe(_win(0))               # no slo_burn: not an observation
    assert eng.observe(_win(1, slo_burn=3.0)) == []  # long not yet full
    eng.observe(_win(2))               # still not an observation
    t = eng.observe(_win(3, slo_burn=3.0))
    assert [x["state"] for x in t] == ["firing"]


# -- absence -----------------------------------------------------------------

def test_absence_batch_fires_only_on_empty_stream():
    eng = AlertEngine([AlertRule("nd", kind="absence", stale_seconds=1)])
    assert eng.finish() and eng.results()[0]["fired"]
    eng2 = AlertEngine([AlertRule("nd", kind="absence", stale_seconds=1)])
    eng2.observe(_win(0))
    assert eng2.finish() == []
    assert not eng2.results()[0]["fired"]


def test_absence_staleness_fires_and_data_resolves():
    eng = AlertEngine([AlertRule("nd", kind="absence",
                                 stale_seconds=0.01)])
    eng.observe(_win(0))
    time.sleep(0.03)
    t = eng.check_staleness()
    assert [x["state"] for x in t] == ["firing"]
    t = eng.observe(_win(1))
    assert [x["state"] for x in t] == ["resolved"]


# -- evaluate_records / defaults --------------------------------------------

def test_evaluate_records_accepts_bare_controller_records():
    recs = [{"window": 0, "durability": {"lost": 0}},
            {"window": 1, "durability": {"lost": 5}}]
    res = {r["name"]: r for r in evaluate_records(recs)}
    assert res["files_lost"]["fired"] and res["files_lost"]["firing"]
    assert res["durability_degraded"]["fired"]
    assert not res["true_lost"]["fired"]
    assert DEFAULT_RULE_NAMES == {r["name"] for r in evaluate_records([])}


# -- CLI: alerts -------------------------------------------------------------

def _write_stream(path, events):
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_alerts_cli_batch_timeline_and_exit(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    _write_stream(p, [_win(0, durability={"lost": 0}),
                      _win(1, durability={"lost": 3}),
                      _win(2, durability={"lost": 0})])
    assert metrics_main(["alerts", str(p)]) == 0
    out = capsys.readouterr().out
    assert "FIRING files_lost [page]" in out
    assert "resolved files_lost" in out
    assert "fired over 3 windows, 0 firing at end" in out
    # still-firing + --fail_firing -> nonzero
    _write_stream(p, [_win(0, durability={"lost": 3})])
    assert metrics_main(["alerts", str(p), "--fail_firing"]) == 1
    assert metrics_main(["alerts", str(p)]) == 0


def test_alerts_cli_batch_dedups_crash_repeated_windows(tmp_path, capsys):
    """A crash/resume tail repeats windows (sink contract) — batch
    verdicts must evaluate the LAST record per window, agreeing with
    summarize/report/watch on the same file."""
    p = tmp_path / "s.jsonl"
    _write_stream(p, [
        _win(0, durability={"lost": 0}),
        _win(1, durability={"lost": 5}),   # stale pre-crash record
        _win(1, durability={"lost": 0}),   # resumed run's last-wins rec
    ])
    assert metrics_main(["alerts", str(p)]) == 0
    out = capsys.readouterr().out
    assert "FIRING files_lost" not in out
    assert "fired over 2 windows" in out


def test_alerts_cli_custom_rules_and_errors(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    _write_stream(p, [_win(0, foo=9)])
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps(
        [{"name": "foo_high", "field": "foo", "value": 5}]),
        encoding="utf-8")
    assert metrics_main(["alerts", str(p), "--rules", str(rules)]) == 0
    assert "FIRING foo_high" in capsys.readouterr().out
    assert metrics_main(["alerts", str(p), "--rules",
                         '[{"name": "x", "bad_key": 1}]']) == 2
    assert "bad --rules" in capsys.readouterr().err
    missing = tmp_path / "nope.jsonl"
    assert metrics_main(["alerts", str(missing)]) == 1
    err = capsys.readouterr().err
    assert "cannot read" in err and "nope.jsonl" in err


def test_alerts_cli_follow_prints_transitions_live(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    _write_stream(p, [_win(0, durability={"lost": 2})])

    def append_later():
        time.sleep(0.1)
        with open(p, "a", encoding="utf-8") as f:
            f.write(json.dumps(_win(1, durability={"lost": 0})) + "\n")

    t = threading.Thread(target=append_later)
    t.start()
    rc = metrics_main(["alerts", str(p), "--follow", "--interval", "0.02",
                       "--max_seconds", "2"])
    t.join()
    assert rc == 0
    out = capsys.readouterr().out
    assert "FIRING files_lost" in out and "resolved files_lost" in out


# -- watch: firing/resolved lines across incremental reads -------------------

def test_watch_renders_firing_then_resolved_across_appends(tmp_path):
    """The watch dashboard must show an ALERT FIRING line while the
    stream is hot and clear it to a resolved note when later windows
    heal — across the same incremental (appending producer) reads the
    truncation-recovery machinery serves."""
    p = tmp_path / "w.jsonl"
    _write_stream(p, [_win(0, durability={"lost": 4})])
    buf = io.StringIO()
    assert watch(str(p), once=True, out=buf) == 0
    first = buf.getvalue()
    assert "ALERT FIRING: files_lost [page] since window 0" in first
    assert "alerts resolved" not in first
    # the producer appends a healed window; a fresh render must clear it
    with open(p, "a", encoding="utf-8") as f:
        f.write(json.dumps(_win(1, durability={"lost": 0})) + "\n")
    buf2 = io.StringIO()
    assert watch(str(p), once=True, out=buf2) == 0
    second = buf2.getvalue()
    assert "ALERT FIRING" not in second
    assert "alerts resolved: files_lost" in second


def test_watch_alert_lines_survive_truncation_recovery(tmp_path):
    """Extend the truncation-recovery contract to alert rendering: after
    rm + fresh producer, the dashboard reflects the NEW stream's alert
    state, not the stale pre-truncation one."""
    p = tmp_path / "w.jsonl"
    _write_stream(p, [_win(0, durability={"lost": 4})])
    buf = io.StringIO()
    assert watch(str(p), once=True, out=buf) == 0
    assert "ALERT FIRING: files_lost" in buf.getvalue()
    os.remove(p)
    _write_stream(p, [_win(0, durability={"lost": 0})])
    buf2 = io.StringIO()
    assert watch(str(p), once=True, out=buf2) == 0
    text = buf2.getvalue()
    assert "ALERT FIRING" not in text and "alerts resolved" not in text


# -- prometheus / summarize / report ----------------------------------------

def test_prometheus_alerts_gauges_for_firing_only():
    events = [_win(0, durability={"lost": 2})]
    lines = prometheus_lines(events)
    assert "# TYPE ALERTS gauge" in lines
    assert ('ALERTS{alertname="files_lost",alertstate="firing",'
            'severity="page"} 1') in lines
    healed = events + [_win(1, durability={"lost": 0})]
    lines = prometheus_lines(healed)
    assert not any(line.startswith("ALERTS{") for line in lines)


def test_summarize_alert_digest(tmp_path):
    def dur(lost):
        return {"lost": lost, "at_risk": 0, "under_replicated": 0,
                "nodes_up": 5}

    out = io.StringIO()
    summarize_events([_win(0, durability=dur(1)),
                      _win(1, durability=dur(0))], out=out)
    text = out.getvalue()
    assert "Alerts: 2 fired (0 still firing at end of stream)" in text
    assert "files_lost" in text and "w0->w1" in text


def test_report_alert_section():
    from cdrs_tpu.obs.report import render_html

    html = render_html([_win(0, durability={"lost": 1})])
    assert "<h2>Alerts</h2>" in html
    assert "files_lost" in html and "firing" in html
    quiet = render_html([_win(0, durability={"lost": 0})])
    assert "<h2>Alerts</h2>" not in quiet


# -- sink rotation -----------------------------------------------------------

def test_sink_rotation_and_ordered_read(tmp_path):
    p = str(tmp_path / "r.jsonl")
    with JsonlSink(p, max_bytes=60) as sink:
        for i in range(12):
            sink.emit({"kind": "counter", "i": i})
    assert os.path.exists(p + ".1") and os.path.exists(p + ".2")
    # every line lands whole in exactly one file of the rotated set
    events = read_events(p)
    assert [e["i"] for e in events] == list(range(12))
    # the live file respects the cap (single oversized lines excepted)
    assert os.path.getsize(p) <= 60
    # iter_events (batch) sees the same contiguous order
    got = [e["i"] for e in iter_events(p)]
    assert got == list(range(12))


def test_sink_rotation_oversized_line_still_lands(tmp_path):
    p = str(tmp_path / "r.jsonl")
    with JsonlSink(p, max_bytes=40) as sink:
        sink.emit({"kind": "x", "blob": "y" * 200})
        sink.emit({"kind": "x", "i": 1})
    events = read_events(p)
    assert len(events) == 2 and events[0]["blob"] == "y" * 200


def test_sink_rotation_rejects_bad_cap(tmp_path):
    with pytest.raises(ValueError, match="max_bytes"):
        JsonlSink(str(tmp_path / "x.jsonl"), max_bytes=0)


def test_iter_events_follow_drains_rotated_tail(tmp_path):
    """A rotation landing between polls: the unread tail of the old file
    (now ``.1``) must be drained before the fresh file's lines."""
    p = str(tmp_path / "r.jsonl")
    sink = JsonlSink(p, max_bytes=120)
    sink.emit({"i": 0})
    got = []

    def consume():
        for e in iter_events(p, follow=True, poll=0.02,
                             stop=lambda: len(got) >= 6):
            got.append(e["i"])

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)   # the follower has read i=0 from the live file
    for i in range(1, 6):
        sink.emit({"i": i})   # forces at least one rotation
    sink.close()
    t.join(timeout=5)
    assert got == list(range(6))


def test_controller_shares_rotating_sink_with_telemetry(tmp_path):
    """`cdrs control --metrics X --metrics_max_bytes N` wiring: the
    controller reuses the active Telemetry's sink on the same path (ONE
    writer — two independently rotating sinks would rename the file out
    from under each other), rotation happens, and the rotated set reads
    back as one stream with every window record present."""
    from cdrs_tpu.config import (
        GeneratorConfig,
        KMeansConfig,
        SimulatorConfig,
        validated_scoring_config,
    )
    from cdrs_tpu.control import ControllerConfig, ReplicationController
    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=120, seed=41))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=600.0, seed=42))
    cfg = ControllerConfig(window_seconds=100.0,
                           kmeans=KMeansConfig(k=6, seed=42),
                           scoring=validated_scoring_config())
    mp = str(tmp_path / "m.jsonl")
    sink = JsonlSink(mp, max_bytes=20_000)
    with Telemetry(sink, meta=False):
        ctl = ReplicationController(manifest, cfg)
        res = ctl.run(events, metrics_path=mp)
        assert sink._f is not None  # run() must NOT close the shared sink
    assert os.path.exists(mp + ".1"), "the stream must have rotated"
    stream = read_events(mp)
    windows = [e for e in stream if e.get("kind") == "window"]
    assert [w["window"] for w in windows] == \
        [r["window"] for r in res.records]


# -- metrics CLI clean errors (summarize | tail | report) --------------------

@pytest.mark.parametrize("action", ["summarize", "tail", "report"])
def test_metrics_cli_missing_file_clean_error(action, tmp_path, capsys):
    rc = metrics_main([action, str(tmp_path / "nope.jsonl")])
    assert rc == 1
    err = capsys.readouterr().err
    assert "nope.jsonl" in err and "\n" == err[-1]
    assert "Traceback" not in err


@pytest.mark.parametrize("action", ["summarize", "tail", "report"])
def test_metrics_cli_empty_file_clean_error(action, tmp_path, capsys):
    p = tmp_path / "empty.jsonl"
    p.write_text("", encoding="utf-8")
    rc = metrics_main([action, str(p)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "no telemetry events" in err and str(p) in err


@pytest.mark.parametrize("action", ["summarize", "tail", "report"])
def test_metrics_cli_corrupt_file_clean_error(action, tmp_path, capsys):
    p = tmp_path / "corrupt.jsonl"
    p.write_bytes(b'{"kind": "window", "window\x00\xff garbage\nmore{{{\n')
    rc = metrics_main([action, str(p)])
    assert rc == 1
    assert "no telemetry events" in capsys.readouterr().err
