"""Checkpoint/resume: blocked Lloyd runs resume identically after a kill."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.ops.kmeans_np import kmeans_plusplus_init
from cdrs_tpu.utils.checkpoint import (
    kmeans_jax_checkpointed,
    load_state,
    save_state,
)


@pytest.fixture()
def blobs():
    rng = np.random.default_rng(3)
    centers = rng.normal(size=(4, 6)) * 4.0
    return np.concatenate(
        [rng.normal(size=(200, 6)) * 0.5 + c for c in centers])


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "s.npz")
    save_state(p, {"a": np.arange(5), "b": np.ones((2, 2))},
               {"it": 7, "note": "x"})
    arrays, meta = load_state(p)
    np.testing.assert_array_equal(arrays["a"], np.arange(5))
    assert meta == {"it": 7, "note": "x"}


def test_checkpointed_matches_uninterrupted(blobs, tmp_path):
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    p1 = str(tmp_path / "a.npz")
    c1, l1, it1 = kmeans_jax_checkpointed(
        blobs, 4, p1, seed=0, max_iter=100, block_iters=100,
        init_centroids=init)
    p2 = str(tmp_path / "b.npz")
    c2, l2, it2 = kmeans_jax_checkpointed(
        blobs, 4, p2, seed=0, max_iter=100, block_iters=3,
        init_centroids=init)
    np.testing.assert_allclose(c1, c2, atol=1e-10)
    assert (l1 == l2).all()


def test_resume_after_kill(blobs, tmp_path):
    """Simulate a crash after the first block; the resumed run must finish
    and match an uninterrupted run."""
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    p = str(tmp_path / "c.npz")
    # "crashed" run: only one block executes
    kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=2, block_iters=2,
                            init_centroids=init, tol=0.0)
    _, meta = load_state(p)
    assert meta["iters_done"] == 2
    # resume to completion
    c2, l2, it2 = kmeans_jax_checkpointed(
        blobs, 4, p, seed=0, max_iter=100, block_iters=50,
        init_centroids=init)
    assert it2 >= 2
    # uninterrupted reference
    pref = str(tmp_path / "d.npz")
    c3, l3, _ = kmeans_jax_checkpointed(
        blobs, 4, pref, seed=0, max_iter=100, block_iters=2,
        init_centroids=init)
    np.testing.assert_allclose(c2, c3, atol=1e-10)
    assert (l2 == l3).all()


def test_resume_from_complete_checkpoint(blobs, tmp_path):
    p = str(tmp_path / "e.npz")
    c1, l1, it1 = kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=50)
    # second invocation: nothing runs (converged flag), identical outputs
    c2, l2, it2 = kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=it1)
    np.testing.assert_allclose(c1, c2, atol=0)
    assert (l2 == l1).all()
    assert it2 == it1


def test_k_mismatch_rejected(blobs, tmp_path):
    p = str(tmp_path / "f.npz")
    kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=2, block_iters=2,
                            tol=0.0)
    with pytest.raises(ValueError, match="checkpoint k="):
        kmeans_jax_checkpointed(blobs, 8, p, seed=0, max_iter=4)


def test_blocked_equivalence_with_reseeds(tmp_path):
    """Reseed draws are keyed by global iteration index, so blocked and
    uninterrupted runs match even when empty-cluster reseeds fire."""
    X = np.array([[0.0, 0], [10, 0], [0, 10], [10, 10], [5, 5]])
    init = np.full((4, 2), 100.0) + np.arange(4)[:, None]  # forces reseeds
    p1 = str(tmp_path / "r1.npz")
    c1, l1, _ = kmeans_jax_checkpointed(X, 4, p1, seed=9, max_iter=40,
                                        block_iters=40, init_centroids=init,
                                        tol=1e-4)
    p2 = str(tmp_path / "r2.npz")
    c2, l2, _ = kmeans_jax_checkpointed(X, 4, p2, seed=9, max_iter=40,
                                        block_iters=1, init_centroids=init,
                                        tol=1e-4)
    np.testing.assert_array_equal(c1, c2)
    assert (l1 == l2).all()


def test_parity_labels_match_uninterrupted(tmp_path):
    """labels='parity' makes a blocked (and resumed-after-complete) run
    label-level drop-in for an uninterrupted kmeans_jax_full run
    (VERDICT r2 weak #7)."""
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(19)
    X = rng.normal(size=(640, 6)).astype(np.float32)
    ck = str(tmp_path / "parity.npz")

    want_c, want_l, want_it, _ = kmeans_jax_full(
        X, 5, tol=1e-4, seed=3, max_iter=40)

    c, l, it = kmeans_jax_checkpointed(
        X, 5, ck, tol=1e-4, seed=3, max_iter=40, block_iters=7,
        labels="parity")
    assert it == want_it
    np.testing.assert_allclose(c, np.asarray(want_c), atol=0)
    np.testing.assert_array_equal(l, np.asarray(want_l))

    # Resume of the already-complete run returns the stored parity labels.
    c2, l2, it2 = kmeans_jax_checkpointed(
        X, 5, ck, tol=1e-4, seed=3, max_iter=40, block_iters=7,
        labels="parity")
    assert it2 == it
    np.testing.assert_array_equal(l2, l)


def test_parity_labels_old_checkpoint_raises(tmp_path):
    rng = np.random.default_rng(20)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    ck = str(tmp_path / "old.npz")
    # Complete a run without parity labels...
    kmeans_jax_checkpointed(X, 3, ck, tol=1e-4, seed=0, max_iter=10,
                            block_iters=5)
    # ...then ask for parity on resume: must fail loudly, not silently
    # return different label semantics.
    with pytest.raises(ValueError, match="parity"):
        kmeans_jax_checkpointed(X, 3, ck, tol=1e-4, seed=0, max_iter=10,
                                block_iters=5, labels="parity")
