"""Checkpoint/resume: blocked Lloyd runs resume identically after a kill."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.ops.kmeans_np import kmeans_plusplus_init
from cdrs_tpu.utils.checkpoint import (
    CheckpointError,
    kmeans_jax_checkpointed,
    load_state,
    save_state,
)


@pytest.fixture()
def blobs():
    rng = np.random.default_rng(3)
    centers = rng.normal(size=(4, 6)) * 4.0
    return np.concatenate(
        [rng.normal(size=(200, 6)) * 0.5 + c for c in centers])


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "s.npz")
    save_state(p, {"a": np.arange(5), "b": np.ones((2, 2))},
               {"it": 7, "note": "x"})
    arrays, meta = load_state(p)
    np.testing.assert_array_equal(arrays["a"], np.arange(5))
    assert meta == {"it": 7, "note": "x"}


def test_corrupt_checkpoint_raises_checkpoint_error(tmp_path):
    """A truncated/garbage npz raises CheckpointError naming the path —
    not numpy's raw zipfile internals."""
    p = str(tmp_path / "torn.npz")
    save_state(p, {"a": np.arange(8)}, {"it": 1})
    with open(p, "r+b") as f:
        f.truncate(40)
    with pytest.raises(CheckpointError, match="torn.npz"):
        load_state(p)
    q = str(tmp_path / "junk.npz")
    with open(q, "wb") as f:
        f.write(b"not an npz at all")
    with pytest.raises(CheckpointError, match="junk.npz"):
        load_state(q)
    # Absent stays FileNotFoundError (the existence-probe contract).
    with pytest.raises(FileNotFoundError):
        load_state(str(tmp_path / "absent.npz"))


def test_save_state_retains_prev_snapshot(tmp_path):
    """Every overwrite renames the previous snapshot to <path>.prev, so a
    corrupted current snapshot always has a one-older fallback."""
    import os

    p = str(tmp_path / "s.npz")
    save_state(p, {"a": np.asarray([1])}, {"gen": 1})
    assert not os.path.exists(p + ".prev")  # first write: nothing to keep
    save_state(p, {"a": np.asarray([2])}, {"gen": 2})
    save_state(p, {"a": np.asarray([3])}, {"gen": 3})
    arrays, meta = load_state(p)
    assert meta["gen"] == 3 and arrays["a"][0] == 3
    arrays_prev, meta_prev = load_state(p + ".prev")
    assert meta_prev["gen"] == 2 and arrays_prev["a"][0] == 2


def test_save_state_fsyncs_before_rename(tmp_path, monkeypatch):
    """The temp npz is fsynced BEFORE the atomic rename: os.replace is
    atomic in the namespace but says nothing about the data, so a host
    crash between write and rename could otherwise land a zero-length/
    torn snapshot at ``path`` — which the NEXT save would hardlink into
    ``.prev``, poisoning the last-good fallback too."""
    import os

    calls: list[tuple[str, object]] = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(
        os, "fsync",
        lambda fd: (calls.append(("fsync", fd)), real_fsync(fd))[1])
    monkeypatch.setattr(
        os, "replace",
        lambda a, b: (calls.append(("replace", b)), real_replace(a, b))[1])
    p = str(tmp_path / "durable.npz")
    save_state(p, {"a": np.arange(3)}, {"gen": 1})
    kinds = [k for k, _ in calls]
    assert "fsync" in kinds, "save_state never fsynced the temp file"
    # The FILE fsync must precede the rename that publishes it (the
    # trailing directory fsync after the rename is fine and expected).
    assert kinds.index("fsync") < kinds.index("replace")
    arrays, meta = load_state(p)
    np.testing.assert_array_equal(arrays["a"], np.arange(3))
    assert meta == {"gen": 1}


def test_checkpointed_matches_uninterrupted(blobs, tmp_path):
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    p1 = str(tmp_path / "a.npz")
    c1, l1, it1 = kmeans_jax_checkpointed(
        blobs, 4, p1, seed=0, max_iter=100, block_iters=100,
        init_centroids=init)
    p2 = str(tmp_path / "b.npz")
    c2, l2, it2 = kmeans_jax_checkpointed(
        blobs, 4, p2, seed=0, max_iter=100, block_iters=3,
        init_centroids=init)
    np.testing.assert_allclose(c1, c2, atol=1e-10)
    assert (l1 == l2).all()


def test_resume_after_kill(blobs, tmp_path):
    """Simulate a crash after the first block; the resumed run must finish
    and match an uninterrupted run."""
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    p = str(tmp_path / "c.npz")
    # "crashed" run: only one block executes
    kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=2, block_iters=2,
                            init_centroids=init, tol=0.0)
    _, meta = load_state(p)
    assert meta["iters_done"] == 2
    # resume to completion
    c2, l2, it2 = kmeans_jax_checkpointed(
        blobs, 4, p, seed=0, max_iter=100, block_iters=50,
        init_centroids=init)
    assert it2 >= 2
    # uninterrupted reference
    pref = str(tmp_path / "d.npz")
    c3, l3, _ = kmeans_jax_checkpointed(
        blobs, 4, pref, seed=0, max_iter=100, block_iters=2,
        init_centroids=init)
    np.testing.assert_allclose(c2, c3, atol=1e-10)
    assert (l2 == l3).all()


def test_resume_from_complete_checkpoint(blobs, tmp_path):
    p = str(tmp_path / "e.npz")
    c1, l1, it1 = kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=50)
    # second invocation: nothing runs (converged flag), identical outputs
    c2, l2, it2 = kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=it1)
    np.testing.assert_allclose(c1, c2, atol=0)
    assert (l2 == l1).all()
    assert it2 == it1


def test_elastic_resume_on_smaller_mesh(blobs, tmp_path):
    """Elastic recovery: a run checkpointed on an 8-device mesh resumes on a
    2-device mesh (e.g. after losing chips) — the snapshot carries only
    mesh-independent state (centroids + the global iteration index), so the
    shrunken-mesh run continues the same trajectory."""
    init = kmeans_plusplus_init(blobs, 4, random_state=0)
    p = str(tmp_path / "elastic.npz")
    kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=4, block_iters=4,
                            init_centroids=init, tol=0.0,
                            mesh_shape={"data": 8})
    _, meta = load_state(p)
    assert meta["iters_done"] == 4   # the mesh-8 snapshot really exists
    c2, l2, it2 = kmeans_jax_checkpointed(
        blobs, 4, p, seed=0, max_iter=30, block_iters=30,
        init_centroids=init, mesh_shape={"data": 2})
    assert it2 >= 4
    # Uninterrupted single-mesh reference: same trajectory up to float
    # reduction order across shard counts.
    pref = str(tmp_path / "elastic_ref.npz")
    c3, l3, _ = kmeans_jax_checkpointed(
        blobs, 4, pref, seed=0, max_iter=30, block_iters=4,
        init_centroids=init, mesh_shape={"data": 2})
    np.testing.assert_allclose(c2, c3, atol=1e-5)
    assert (l2 == l3).mean() > 0.999


def test_stream_elastic_resume_cross_mesh(tmp_path, crash_fold_after):
    """The stream-fold checkpoint is mesh-independent: crash while folding on
    a data=8 mesh, resume on data=2 — bit-identical features (the counters
    are int32, so no reduction-order drift exists at all)."""
    import os

    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.features import streaming as S
    from cdrs_tpu.features.numpy_backend import compute_features
    from cdrs_tpu.io.events import EventLog
    from cdrs_tpu.runtime.native import native_available
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    if not native_available():
        pytest.skip("checkpoint offsets need the native parser")

    manifest = generate_population(GeneratorConfig(n_files=80, seed=5))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=60.0, seed=5))
    log = str(tmp_path / "a.log")
    events.write_csv(log, manifest)
    golden = compute_features(manifest, EventLog.read_csv(log, manifest))

    ckpt = str(tmp_path / "s.ckpt.npz")
    restore = crash_fold_after(3, "chip lost")
    with pytest.raises(RuntimeError, match="chip lost"):
        S.fold_stream(log, manifest, batch_size=400,
                      mesh_shape={"data": 8},
                      checkpoint_path=ckpt, checkpoint_every=1)
    restore()
    assert os.path.exists(ckpt)   # the crash run really snapshotted

    stats = {}
    state = S.fold_stream(log, manifest, batch_size=400,
                          mesh_shape={"data": 2}, checkpoint_path=ckpt,
                          stats=stats)
    assert stats["resumed_from_offset"] > 0   # ...and the resume used it
    got = S.stream_finalize(state, manifest)
    np.testing.assert_array_equal(np.asarray(got.raw),
                                  np.asarray(golden.raw))


def test_k_mismatch_rejected(blobs, tmp_path):
    p = str(tmp_path / "f.npz")
    kmeans_jax_checkpointed(blobs, 4, p, seed=0, max_iter=2, block_iters=2,
                            tol=0.0)
    with pytest.raises(ValueError, match="checkpoint k="):
        kmeans_jax_checkpointed(blobs, 8, p, seed=0, max_iter=4)


def test_blocked_equivalence_with_reseeds(tmp_path):
    """Reseed draws are keyed by global iteration index, so blocked and
    uninterrupted runs match even when empty-cluster reseeds fire."""
    X = np.array([[0.0, 0], [10, 0], [0, 10], [10, 10], [5, 5]])
    init = np.full((4, 2), 100.0) + np.arange(4)[:, None]  # forces reseeds
    p1 = str(tmp_path / "r1.npz")
    c1, l1, _ = kmeans_jax_checkpointed(X, 4, p1, seed=9, max_iter=40,
                                        block_iters=40, init_centroids=init,
                                        tol=1e-4)
    p2 = str(tmp_path / "r2.npz")
    c2, l2, _ = kmeans_jax_checkpointed(X, 4, p2, seed=9, max_iter=40,
                                        block_iters=1, init_centroids=init,
                                        tol=1e-4)
    np.testing.assert_array_equal(c1, c2)
    assert (l1 == l2).all()


def test_parity_labels_match_uninterrupted(tmp_path):
    """labels='parity' makes a blocked (and resumed-after-complete) run
    label-level drop-in for an uninterrupted kmeans_jax_full run
    (VERDICT r2 weak #7)."""
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(19)
    X = rng.normal(size=(640, 6)).astype(np.float32)
    ck = str(tmp_path / "parity.npz")

    want_c, want_l, want_it, _ = kmeans_jax_full(
        X, 5, tol=1e-4, seed=3, max_iter=40)

    c, lab, it = kmeans_jax_checkpointed(
        X, 5, ck, tol=1e-4, seed=3, max_iter=40, block_iters=7,
        labels="parity")
    assert it == want_it
    np.testing.assert_allclose(c, np.asarray(want_c), atol=0)
    np.testing.assert_array_equal(lab, np.asarray(want_l))

    # Resume of the already-complete run returns the stored parity labels.
    c2, l2, it2 = kmeans_jax_checkpointed(
        X, 5, ck, tol=1e-4, seed=3, max_iter=40, block_iters=7,
        labels="parity")
    assert it2 == it
    np.testing.assert_array_equal(l2, lab)


def test_parity_labels_old_checkpoint_raises(tmp_path):
    rng = np.random.default_rng(20)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    ck = str(tmp_path / "old.npz")
    # Complete a run without parity labels...
    kmeans_jax_checkpointed(X, 3, ck, tol=1e-4, seed=0, max_iter=10,
                            block_iters=5)
    # ...then ask for parity on resume: must fail loudly, not silently
    # return different label semantics.
    with pytest.raises(ValueError, match="parity"):
        kmeans_jax_checkpointed(X, 3, ck, tol=1e-4, seed=0, max_iter=10,
                                block_iters=5, labels="parity")
