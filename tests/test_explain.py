"""Decision provenance: explain hooks, cause tagging, lineage, CLI.

The acceptance contract (ISSUE 15): ``cdrs explain file`` output is
decision-faithful — the narrated slot choices reproduce
``compute_placement`` exactly (property-tested on seeds 0/1/2, flat +
hierarchical topologies, and against BOTH hash placement surfaces:
functional recompute and the materialized_hash placement rows) — and
every explained move's cause tag matches the controller record that
produced it.
"""

import os

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, place_replicas
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ReplicationController
from cdrs_tpu.control.controller import ControllerConfig, MOVE_CAUSES
from cdrs_tpu.obs import JsonlSink, Telemetry, read_events
from cdrs_tpu.obs.explain import (
    explain_category,
    explain_window,
    file_history,
    main as explain_main,
)
from cdrs_tpu.placement_fn import (
    compute_placement,
    explain_placement,
    primary_on_topology,
)
from cdrs_tpu.sim.access import simulate_access_with_shift
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))

_GEO = {
    "nodes": [f"dn{i}" for i in range(1, 13)],
    "levels": ["rack", "region"],
    "rack": {f"r{j}": [f"dn{2 * j + 1}", f"dn{2 * j + 2}"]
             for j in range(6)},
    "region": {"eu": ["r0", "r1"], "us": ["r2", "r3"],
               "ap": ["r4", "r5"]},
}


def _topologies():
    return [
        ("flat", ClusterTopology(nodes=("dn1", "dn2", "dn3", "dn4",
                                        "dn5"))),
        ("racked", ClusterTopology.from_rack_spec(
            ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6"),
            "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6")),
        ("geo", ClusterTopology.from_hierarchy(_GEO)),
    ]


# -- explain_placement: decision-faithful by property ------------------------

@pytest.mark.parametrize("name,topology", _topologies())
def test_explain_placement_matches_compute_placement(name, topology):
    """The narration's slots equal the vector chooser's row for every
    (file, rf, primary) tried — explain_placement raises on divergence,
    so surviving the sweep IS the property."""
    n = len(topology)
    for seed in (SEED, SEED + 1, SEED + 2):
        for fid in range(60):
            for rf in (1, 2, 3, min(5, n), n):
                d = explain_placement(fid, rf, fid % n, topology, seed)
                want, want_rf = compute_placement(
                    np.asarray([fid]), np.asarray([rf], np.int32),
                    np.asarray([fid % n]), topology, seed)
                assert [s["node"] for s in d["slots"]] == \
                    [int(x) for x in want[0, :int(want_rf[0])]]


def test_explain_placement_matches_materialized_hash_rows():
    """The same chooser materialized (place_replicas(method='hash') —
    the materialized_hash mode's placement) agrees with the narration
    row for row."""
    for name, topology in _topologies():
        nodes = topology.nodes
        manifest = generate_population(GeneratorConfig(
            n_files=80, seed=SEED + 3, nodes=tuple(nodes)))
        rf = np.full(80, 3, dtype=np.int32)
        placement = place_replicas(manifest, rf, topology, seed=0,
                                   method="hash")
        primary = primary_on_topology(manifest.nodes,
                                      manifest.primary_node_id, topology)
        for fid in range(0, 80, 7):
            d = explain_placement(fid, 3, int(primary[fid]), topology, 0)
            row = placement.replica_map[fid]
            assert [s["node"] for s in d["slots"]] == \
                [int(x) for x in row[:int(placement.rf[fid])]]


def test_explain_placement_region_local_masks_off_region():
    topo = ClusterTopology.from_hierarchy(_GEO)
    d = explain_placement(5, 3, 0, topo, SEED, local=True)
    masked = [c for s in d["slots"] for c in s.get("candidates", ())
              if c.get("masked") == "off-region (locality pin)"]
    assert masked, "off-region candidates must be visibly masked"
    # and the chosen nodes all sit in the primary's region
    top = topo.top_domain_index()
    assert all(top[s["node"]] == top[0] for s in d["slots"])


def test_explain_placement_slot_rules_flat_vs_racked():
    flat = _topologies()[0][1]
    d = explain_placement(3, 3, 1, flat, 0)
    assert d["slots"][0]["rule"] == "primary"
    assert all("ascending hash priority" == s["rule"]
               for s in d["slots"][1:])
    racked = _topologies()[1][1]
    d = explain_placement(3, 3, 1, racked, 0)
    assert "remote domain" in d["slots"][1]["rule"]


# -- score decomposition (Table-2 math) --------------------------------------

def test_score_terms_sum_to_score_table_exactly():
    from cdrs_tpu.config import ScoringConfig
    from cdrs_tpu.ops.scoring_np import score_table, score_table_terms

    rng = np.random.default_rng(SEED)
    for cfg in (ScoringConfig(), validated_scoring_config()):
        medians = rng.uniform(0, 1, size=(8, len(cfg.features)))
        medians[2, 1] = np.nan  # empty-cluster row
        terms = score_table_terms(medians, cfg)
        assert np.array_equal(terms.sum(axis=2),
                              score_table(medians, cfg))


def test_explain_category_contributions_reconcile():
    cfg = validated_scoring_config()
    rng = np.random.default_rng(SEED + 1)
    cent = rng.uniform(0, 1, size=(6, len(cfg.features)))
    from cdrs_tpu.ops.scoring_np import classify_medians

    cat_idx, scores = classify_medians(cent, cfg)
    from cdrs_tpu.config import CATEGORIES

    for ci, name in enumerate(CATEGORIES):
        d = explain_category(name, cent, cat_idx, cfg)
        for c in d["clusters"]:
            total = round(sum(f["contribution"] for f in c["features"]), 4)
            assert total == round(c["score"], 4)
            assert c["scores_all"][name] == c["score"]
            # the decomposition's argmax agrees with the decision here
            # (same representative in = same scores out)
            assert c["margin"] >= 0


# -- controller cause tagging + lineage --------------------------------------

@pytest.fixture(scope="module")
def chaos_stream(tmp_path_factory):
    """One fault-mode controller run with telemetry: records + stream."""
    from cdrs_tpu.faults import FaultSchedule

    td = tmp_path_factory.mktemp("explain")
    manifest = generate_population(GeneratorConfig(n_files=250,
                                                   seed=SEED + 11))
    events, _ = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=1500.0, seed=SEED + 12),
        750.0, {"hot": "archival", "archival": "hot"})
    cfg = ControllerConfig(
        window_seconds=100.0, kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(), default_rf=2,
        drift_threshold=0.02, placement_mode="materialized_hash",
        fault_schedule=FaultSchedule.from_specs(["crash:dn2@5-9"]))
    mp = str(td / "m.jsonl")
    ck = str(td / "c.npz")
    with Telemetry(JsonlSink(mp), meta=False):
        res = ReplicationController(manifest, cfg).run(
            events, metrics_path=mp, checkpoint_path=ck)
    return {"manifest": manifest, "events": events, "cfg": cfg,
            "records": res.records, "stream": read_events(mp),
            "metrics_path": mp, "checkpoint_path": ck, "dir": td}


def test_lineage_events_match_window_cause_records(chaos_stream):
    """Acceptance: every lineage batch's cause/files/bytes reconciles
    with the ``causes`` digest of the window record that produced it."""
    stream = chaos_stream["stream"]
    lineage = [e for e in stream if e.get("kind") == "lineage"]
    assert lineage, "a drifting fault run must emit lineage"
    assert {e["cause"] for e in lineage} >= {"drift", "repair"}
    by_window: dict = {}
    for e in lineage:
        agg = by_window.setdefault(e["window"], {})
        c = agg.setdefault(e["cause"], {"files": 0, "bytes": 0})
        c["files"] += e["files"]
        c["bytes"] += e["bytes"]
        assert len(e["file_ids"]) == e["files"]  # under the id cap here
    for rec in chaos_stream["records"]:
        assert by_window.get(rec["window"], {}) == \
            (rec.get("causes") or {})


def test_lineage_totals_match_record_traffic(chaos_stream):
    for rec in chaos_stream["records"]:
        causes = rec.get("causes") or {}
        mig = sum(v["bytes"] for k, v in causes.items()
                  if k in MOVE_CAUSES.values())
        assert mig == rec["bytes_migrated"]
        rep = (causes.get("repair", {}).get("bytes", 0)
               + causes.get("correlated_rebalance", {}).get("bytes", 0))
        assert rep == rec.get("repair_bytes", 0)


def test_file_history_matches_records(chaos_stream):
    stream = chaos_stream["stream"]
    lineage = [e for e in stream if e.get("kind") == "lineage"]
    fid = lineage[0]["file_ids"][0]
    hist = file_history(stream, fid)
    assert hist
    recs = {r["window"]: r for r in chaos_stream["records"]}
    for h in hist:
        rec = recs[h["window"]]
        assert h["cause"] in (rec.get("causes") or {})
        assert h["plan_hash"] == rec["plan_hash"]


def test_cause_tags_survive_kill_resume(chaos_stream):
    """A resumed controller must report the same causes as the
    uninterrupted run — the cause vector rides the checkpoint."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "c.npz")
        cfg = chaos_stream["cfg"]
        manifest = chaos_stream["manifest"]
        events = chaos_stream["events"]
        a = ReplicationController(manifest, cfg).run(
            events, checkpoint_path=ck, max_windows=6)
        b = ReplicationController(manifest, cfg).run(
            events, checkpoint_path=ck)
        strip = [{k: v for k, v in r.items() if k != "seconds"}
                 for r in a.records + b.records]
        want = [{k: v for k, v in r.items() if k != "seconds"}
                for r in chaos_stream["records"]]
        assert strip == want


def test_explain_window_ranks_crossed_signals(chaos_stream):
    stream = chaos_stream["stream"]
    crash = next(r["window"] for r in chaos_stream["records"]
                 if r.get("fault_events"))
    d = explain_window(stream, crash)
    crossed = [s["signal"] for s in d["signals"] if s["crossed"]]
    assert any(s.startswith("durability.") for s in crossed)
    assert d["signals"][0]["crossed"]  # crossed ranked first
    assert "repair" in d["traffic"]
    assert d["traffic_bytes_total"] >= d["repair_bytes"]
    with pytest.raises(ValueError, match="no window 999"):
        explain_window(stream, 999)


# -- the CLI: golden-stable, decision-faithful -------------------------------

def _manifest_csv(chaos_stream):
    p = str(chaos_stream["dir"] / "manifest.csv")
    if not os.path.exists(p):
        chaos_stream["manifest"].write_csv(p)
    return p


def test_explain_file_cli_stable_and_faithful(chaos_stream, capsys):
    mpath = _manifest_csv(chaos_stream)
    argv = ["file", "3", "--manifest", mpath,
            "--metrics", chaos_stream["metrics_path"],
            "--checkpoint", chaos_stream["checkpoint_path"]]
    assert explain_main(argv) == 0
    first = capsys.readouterr().out
    assert explain_main(argv) == 0
    assert capsys.readouterr().out == first  # golden-stable
    assert "computed placement" in first and "slot 0" in first
    assert "move history" in first


def test_explain_category_cli(chaos_stream, capsys):
    assert explain_main(["category", "Hot", "--checkpoint",
                         chaos_stream["checkpoint_path"],
                         "--scoring_config", "validated"]) == 0
    out = capsys.readouterr().out
    assert "category Hot" in out
    assert explain_main(["category", "Bogus", "--checkpoint",
                         chaos_stream["checkpoint_path"]]) == 2
    assert "unknown category" in capsys.readouterr().err


def test_explain_window_cli(chaos_stream, capsys):
    assert explain_main(["window", "5", "--metrics",
                         chaos_stream["metrics_path"]]) == 0
    out = capsys.readouterr().out
    assert "signals (crossed first):" in out
    assert explain_main(["window", "999", "--metrics",
                         chaos_stream["metrics_path"]]) == 2


def test_explain_file_cli_rejects_materialized_checkpoint(tmp_path,
                                                          capsys):
    manifest = generate_population(GeneratorConfig(n_files=60,
                                                   seed=SEED + 20))
    events, _ = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=300.0, seed=SEED + 21),
        150.0, {"hot": "archival"})
    cfg = ControllerConfig(window_seconds=100.0,
                           kmeans=KMeansConfig(k=6, seed=42),
                           scoring=validated_scoring_config())
    ck = str(tmp_path / "c.npz")
    ReplicationController(manifest, cfg).run(events, checkpoint_path=ck)
    mpath = str(tmp_path / "m.csv")
    manifest.write_csv(mpath)
    rc = explain_main(["file", "0", "--manifest", mpath,
                       "--checkpoint", ck])
    assert rc == 2
    assert "materialized" in capsys.readouterr().err


def test_explain_file_out_of_range_clean_error(chaos_stream, capsys):
    """Out-of-range ids error cleanly even with a checkpoint (the range
    check must run before any checkpoint array is indexed)."""
    mpath = _manifest_csv(chaos_stream)
    rc = explain_main(["file", "99999", "--manifest", mpath,
                       "--checkpoint", chaos_stream["checkpoint_path"]])
    assert rc == 2
    assert "out of range" in capsys.readouterr().err


def test_explain_cli_clean_errors(tmp_path, capsys):
    rc = explain_main(["window", "1", "--metrics",
                       str(tmp_path / "nope.jsonl")])
    assert rc == 1
    assert "cannot read" in capsys.readouterr().err


def test_lineage_id_cap_truncates_ids_not_counts(monkeypatch):
    import cdrs_tpu.control.controller as ctl_mod

    monkeypatch.setattr(ctl_mod, "LINEAGE_ID_CAP", 5)
    manifest = generate_population(GeneratorConfig(n_files=120,
                                                   seed=SEED + 30))
    events, _ = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=400.0, seed=SEED + 31),
        200.0, {"hot": "archival", "archival": "hot"})
    cfg = ControllerConfig(window_seconds=100.0,
                           kmeans=KMeansConfig(k=6, seed=42),
                           scoring=validated_scoring_config(),
                           drift_threshold=0.02)
    captured: list = []

    class _Cap:
        def emit(self, e):
            captured.append(e)

        def close(self):
            pass

    with Telemetry(_Cap(), meta=False):
        ReplicationController(manifest, cfg).run(events)
    lin = [e for e in captured if e.get("kind") == "lineage"]
    big = [e for e in lin if e["files"] > 5]
    assert big, "the cold-start plan moves >5 files"
    for e in big:
        assert e["truncated"] and len(e["file_ids"]) == 5
