"""Collect-only marker discipline audit (ISSUE 2 satellite).

Tier-1 runs ``-m 'not slow'`` on a jax-optional CPU host, so two contracts
keep the suite runnable everywhere:

* any test module that exercises **real-chip** paths (gated on
  ``CDRS_TPU_TESTS`` / a TPU backend) must skip itself at module level (or
  carry the ``tpu``/``slow`` marker) so the CPU-mesh run never collects
  chip work;
* any test module importing jax at module level must guard with
  ``pytest.importorskip("jax")`` first, so a base install (no ``tpu``
  extra) still collects the numpy suite.

Pure source inspection — no test modules are imported, so the audit runs
even when their imports would fail.
"""

import re
from pathlib import Path

TESTS_DIR = Path(__file__).parent
SELF = Path(__file__).name


def _test_modules():
    return [p for p in sorted(TESTS_DIR.glob("test_*.py"))
            if p.name != SELF]


def test_real_chip_modules_are_gated():
    offenders = []
    for path in _test_modules():
        src = path.read_text()
        uses_chip = ("CDRS_TPU_TESTS" in src
                     or 'default_backend() == "tpu"' in src)
        if not uses_chip:
            continue
        gated = ("allow_module_level=True" in src
                 or "pytest.mark.tpu" in src
                 or "pytest.mark.slow" in src)
        if not gated:
            offenders.append(path.name)
    assert not offenders, (
        f"modules touching real-TPU paths without a module-level skip or "
        f"tpu/slow marker: {offenders}")


def test_module_level_jax_imports_are_guarded():
    pattern = re.compile(r"^(?:import jax\b|from jax)", re.M)
    offenders = []
    for path in _test_modules():
        src = path.read_text()
        m = pattern.search(src)
        if m is None:
            continue
        guard = src.find('importorskip("jax")')
        if guard == -1 or guard > m.start():
            offenders.append(path.name)
    assert not offenders, (
        f"modules importing jax at module scope without a preceding "
        f'pytest.importorskip("jax"): {offenders}')


def test_markers_are_registered():
    """The slow/tpu markers tier-1 filters on must be declared in
    pyproject (typo'd marks otherwise silently match nothing)."""
    root = TESTS_DIR.parent / "pyproject.toml"
    src = root.read_text()
    assert "markers" in src and "slow:" in src and "tpu:" in src
