"""Online replication controller (control/): windows, drift, migration
scheduling, loop determinism, and checkpoint kill/resume bit-equality."""

import json

import numpy as np
import pytest

from cdrs_tpu.config import (
    CATEGORIES,
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import (
    ControllerConfig,
    MigrationScheduler,
    PlanMove,
    ReplicationController,
    detect_drift,
    iter_windows,
    plan_diff,
)
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.sim.access import simulate_access, simulate_access_with_shift
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=200, seed=21))
    events = simulate_access(manifest,
                             SimulatorConfig(duration_seconds=600.0, seed=22))
    return manifest, events


def _cfg(**kw):
    base = dict(window_seconds=120.0,
                kmeans=KMeansConfig(k=8, seed=42),
                scoring=validated_scoring_config())
    base.update(kw)
    return ControllerConfig(**base)


# -- windows ----------------------------------------------------------------

def test_iter_windows_batch_size_invariant(workload):
    manifest, events = workload

    def collect(batches):
        return list(iter_windows(batches, manifest, 60.0))

    whole = collect(events)
    # Re-batch the same log at an awkward size; windows must be identical.
    split = [EventLog(ts=events.ts[i:i + 777],
                      path_id=events.path_id[i:i + 777],
                      op=events.op[i:i + 777],
                      client_id=events.client_id[i:i + 777],
                      clients=events.clients)
             for i in range(0, len(events), 777)]
    rebatched = collect(split)
    assert [w for w, _ in whole] == [w for w, _ in rebatched]
    for (_, a), (_, b) in zip(whole, rebatched):
        np.testing.assert_array_equal(a.ts, b.ts)
        np.testing.assert_array_equal(a.path_id, b.path_id)
    # Consecutive indices from 0, each window inside its time span.
    t0 = float(np.floor(events.ts[0]))
    for w, win in whole:
        if len(win):
            assert t0 + w * 60.0 <= win.ts[0] and win.ts[-1] < t0 + (w + 1) * 60.0
    assert [w for w, _ in whole] == list(range(len(whole)))


def test_iter_windows_yields_empty_gap_windows(workload):
    manifest, events = workload
    # Splice a 5-window silence into the middle of the log.
    half = len(events) // 2
    ts = events.ts.copy()
    ts[half:] += 600.0
    gappy = EventLog(ts=ts, path_id=events.path_id, op=events.op,
                     client_id=events.client_id, clients=events.clients)
    wins = list(iter_windows(gappy, manifest, 120.0))
    empty = [w for w, win in wins if len(win) == 0]
    assert empty, "the silence must surface as empty windows"
    assert [w for w, _ in wins] == list(range(len(wins)))


def test_iter_windows_rejects_unsorted(workload):
    manifest, events = workload
    bad = EventLog(ts=events.ts[::-1].copy(), path_id=events.path_id,
                   op=events.op, client_id=events.client_id,
                   clients=events.clients)
    with pytest.raises(ValueError, match="time-sorted"):
        list(iter_windows(bad, manifest, 60.0))


# -- drift ------------------------------------------------------------------

def test_drift_zero_on_unchanged_features():
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0.2, 0.02, (150, 5)),
                        rng.normal(0.8, 0.02, (150, 5))]).clip(0, 1)
    from cdrs_tpu.ops.kmeans_np import kmeans

    centroids, labels = kmeans(X, 2, random_state=0)
    cat_idx = np.asarray([0, 3])
    frac = np.bincount(cat_idx[labels], minlength=len(CATEGORIES)) / len(X)
    rep = detect_drift(X, centroids, cat_idx, frac, len(CATEGORIES))
    assert rep.score < 1e-3  # converged model over the same data: no drift
    # Shift half the population: both signals must fire.
    X2 = X.copy()
    X2[:150] = (X2[:150] + 0.6).clip(0, 1)
    rep2 = detect_drift(X2, centroids, cat_idx, frac, len(CATEGORIES))
    assert rep2.score > 0.1
    assert rep2.centroid_shift > 0.0 and rep2.population_delta > 0.0


# -- plan diff + scheduler --------------------------------------------------

def test_plan_diff_moves_and_byte_cost():
    rf_old = np.asarray([1, 1, 3, 4])
    rf_new = np.asarray([3, 1, 1, 4])
    cat_old = np.asarray([2, 2, 0, 3])
    cat_new = np.asarray([0, 2, 2, 3])
    sizes = np.asarray([100, 200, 300, 400])
    moves = plan_diff(rf_old, rf_new, cat_old, cat_new, sizes,
                      priority=[5.0, 0.0, 1.0, 0.0])
    assert [m.file_index for m in moves] == [0, 2]
    up, down = moves
    assert up.bytes_moved == 100 * 2      # rf 1 -> 3: two new replicas
    assert down.bytes_moved == 0          # rf 3 -> 1: drops are free
    assert up.priority == 5.0


def test_scheduler_budget_and_hysteresis():
    moves = [PlanMove(i, 1, 3, 2, 0, bytes_moved=100, priority=float(10 - i))
             for i in range(10)]
    s = MigrationScheduler(10, max_bytes_per_window=250,
                           max_files_per_window=None, hysteresis_windows=2)
    s.submit(moves)
    first = s.schedule(0)
    # Priority order, byte budget: two 100-byte moves fit under 250.
    assert [m.file_index for m in first] == [0, 1]
    # Files 0/1 moved at window 0 + hysteresis 2 -> frozen until window 3.
    s.submit(moves)  # resubmit everything, including just-moved files
    second = s.schedule(1)
    assert all(m.file_index not in (0, 1) for m in second)
    assert [m.file_index for m in second] == [2, 3]
    assert 0 not in {m.file_index for m in s.schedule(2)}
    assert {m.file_index for m in s.schedule(3)} <= {0, 1, 6, 7, 8, 9}


def test_scheduler_oversized_move_does_not_starve():
    s = MigrationScheduler(3, max_bytes_per_window=50)
    s.submit([PlanMove(0, 1, 3, 2, 0, bytes_moved=500, priority=1.0)])
    assert [m.file_index for m in s.schedule(0)] == [0]  # sole oversized move
    s.submit([PlanMove(1, 1, 3, 2, 0, bytes_moved=500, priority=1.0),
              PlanMove(0, 1, 2, 2, 0, bytes_moved=40, priority=2.0)])
    s.last_moved[:] = -(2 ** 40)
    got = s.schedule(1)
    # The small move fits first; the oversized one must then wait.
    assert [m.file_index for m in got] == [0]


def test_scheduler_zero_budget_freezes_byte_moves():
    """max_bytes_per_window=0 is a true freeze: no byte-moving move runs
    (the oversized allowance needs a positive budget); metadata-only
    moves still drain."""
    s = MigrationScheduler(2, max_bytes_per_window=0)
    s.submit([PlanMove(0, 1, 3, 2, 0, bytes_moved=100, priority=9.0),
              PlanMove(1, 4, 1, 3, 2, bytes_moved=0, priority=1.0)])
    for w in range(3):
        assert all(m.bytes_moved == 0 for m in s.schedule(w))
    assert 0 in s.backlog and 1 not in s.backlog


def test_scheduler_zero_byte_moves_never_byte_blocked():
    """Replica drops are metadata operations: the byte budget must not
    defer them, even after an oversized move overdrew the window."""
    s = MigrationScheduler(3, max_bytes_per_window=50)
    s.submit([PlanMove(0, 1, 3, 2, 0, bytes_moved=500, priority=3.0),
              PlanMove(1, 4, 1, 3, 2, bytes_moved=0, priority=2.0),
              PlanMove(2, 3, 1, 0, 2, bytes_moved=0, priority=1.0)])
    got = s.schedule(0)
    assert [m.file_index for m in got] == [0, 1, 2]


def test_scheduler_file_cap_under_full_population_flip(workload):
    """Churn cap honored while a forced full-population flip drains."""
    manifest, events = workload
    cap = 23
    cfg = _cfg(max_files_per_window=cap, hysteresis_windows=0,
               drift_threshold=10.0)  # only the cold start re-clusters
    ctl = ReplicationController(manifest, cfg)
    res = ctl.run(events)
    assert all(r["moves_applied"] <= cap for r in res.records)
    # The cold-start plan covers every file; the backlog must drain at the
    # cap's pace, never faster.
    applied = np.cumsum([r["moves_applied"] for r in res.records])
    # Exactly at the cap's pace: the backlog is deep enough to saturate
    # every window of this log.
    assert applied[-1] == min(len(manifest), cap * len(res.records))
    assert all(a <= cap * (i + 1) for i, a in enumerate(applied))


def test_controller_byte_cap_respected(workload):
    manifest, events = workload
    # Cap safely above the largest single move (max size x rf delta <= 3)
    # so the oversized-move allowance can never fire.
    cap = int(np.max(manifest.size_bytes)) * 3 + 1
    cfg = _cfg(max_bytes_per_window=cap, hysteresis_windows=0)
    res = ReplicationController(manifest, cfg).run(events)
    assert all(r["bytes_migrated"] <= cap for r in res.records)
    assert sum(r["moves_applied"] for r in res.records) > 0


# -- the loop ---------------------------------------------------------------

def test_controller_deterministic(workload):
    manifest, events = workload
    runs = []
    for _ in range(2):
        res = ReplicationController(manifest, _cfg(decay=0.8)).run(events)
        runs.append([r["plan_hash"] for r in res.records])
    assert runs[0] == runs[1]


def test_controller_stationary_log_drift_noop(workload):
    """On a stationary workload only the cold start re-clusters (the drift
    detector reports scores under the threshold for every later window)."""
    manifest, events = workload
    res = ReplicationController(manifest, _cfg(drift_threshold=0.15)).run(
        events)
    assert res.records[0]["recluster_mode"] == "full"  # cold start
    later = res.records[1:]
    assert later and all(not r["recluster"] for r in later)
    assert all(r["drift"] < 0.15 for r in later if r["drift"] is not None)


def test_controller_kill_resume_bit_identical(tmp_path, workload):
    manifest, events = workload
    cfg = dict(decay=0.8, max_files_per_window=40, hysteresis_windows=1)

    ref = ReplicationController(manifest, _cfg(**cfg)).run(events)
    ref_hashes = [r["plan_hash"] for r in ref.records]
    assert len(ref_hashes) >= 4

    ck = str(tmp_path / "ctl.npz")
    a = ReplicationController(manifest, _cfg(**cfg)).run(
        events, checkpoint_path=ck, max_windows=2)  # "killed" after 2 windows
    b = ReplicationController(manifest, _cfg(**cfg)).run(
        events, checkpoint_path=ck)                 # resumes from snapshot
    assert [r["window"] for r in b.records] == \
        list(range(2, len(ref_hashes)))
    got = [r["plan_hash"] for r in a.records] + \
        [r["plan_hash"] for r in b.records]
    assert got == ref_hashes
    np.testing.assert_array_equal(b.rf, ref.rf)
    np.testing.assert_array_equal(b.category_idx, ref.category_idx)


def test_controller_resume_over_grown_log_folds_tail(tmp_path, workload):
    """Resuming over a grown append-only log must fold the events that
    landed in the previously-final partial window — no silent undercount
    in the carried feature state."""
    from cdrs_tpu.features.streaming_np import stream_init_np, \
        stream_update_np

    manifest, events = workload
    t0 = float(np.floor(events.ts[0]))
    # Truncate mid-way through the final 120 s window of the 600 s log.
    cut = int(np.searchsorted(events.ts, t0 + 540.0))
    assert 0 < cut < len(events)
    first = EventLog(ts=events.ts[:cut], path_id=events.path_id[:cut],
                     op=events.op[:cut], client_id=events.client_id[:cut],
                     clients=events.clients)

    ck = str(tmp_path / "grow.npz")
    ctl = ReplicationController(manifest, _cfg())
    ctl.run(first, checkpoint_path=ck)

    resumed = ReplicationController(manifest, _cfg())
    res = resumed.run(events, checkpoint_path=ck)
    assert res.records == []  # no new complete window: fold-only resume
    assert resumed._events_total == len(events)
    pure = stream_update_np(stream_init_np(len(manifest)), events, manifest)
    np.testing.assert_array_equal(resumed._state.access_freq,
                                  pure.access_freq)
    np.testing.assert_array_equal(resumed._state.conc_max, pure.conc_max)
    # The tail fold was snapshotted: a THIRD run over the same log is a
    # clean no-op, not a re-fold.
    third = ReplicationController(manifest, _cfg())
    third.run(events, checkpoint_path=ck)
    assert third._events_total == len(events)
    np.testing.assert_array_equal(third._state.access_freq, pure.access_freq)


def test_controller_max_windows_zero_is_a_noop(tmp_path, workload):
    """max_windows=0 must mutate nothing — the state-inspection call."""
    manifest, events = workload
    ck = str(tmp_path / "noop.npz")
    ctl = ReplicationController(manifest, _cfg())
    res = ctl.run(events, checkpoint_path=ck, max_windows=0)
    assert res.records == [] and ctl._events_total == 0
    assert ctl.window_index == 0
    import os

    assert not os.path.exists(ck)  # nothing processed, nothing snapshotted


def test_controller_checkpoint_config_mismatch(tmp_path, workload):
    manifest, events = workload
    ck = str(tmp_path / "ctl.npz")
    ReplicationController(manifest, _cfg()).run(events, checkpoint_path=ck,
                                                max_windows=1)
    other = _cfg(kmeans=KMeansConfig(k=12, seed=42))
    with pytest.raises(ValueError, match="stale checkpoint"):
        ReplicationController(manifest, other).run(events,
                                                   checkpoint_path=ck)


def test_controller_metrics_jsonl_sink(tmp_path, workload):
    manifest, events = workload
    mp = str(tmp_path / "metrics.jsonl")
    res = ReplicationController(manifest, _cfg()).run(events,
                                                      metrics_path=mp)
    lines = [json.loads(ln) for ln in open(mp)]
    assert len(lines) == len(res.records)
    assert lines[0]["window"] == 0 and "plan_hash" in lines[-1]
    assert set(lines[0]["seconds"]) >= {"fold", "drift", "recluster",
                                        "schedule", "evaluate", "total"}


def test_controller_decay_adapts_to_shift():
    """After a hot<->archival cohort flip the decayed controller re-plans the
    cohort toward its new categories (the control loop's reason to exist)."""
    from cdrs_tpu.config import PLANTED_TO_CATEGORY

    manifest = generate_population(GeneratorConfig(n_files=300, seed=7))
    flip = {"hot": "archival", "archival": "hot"}
    events, flipped = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=1200.0, seed=8),
        shift_at=600.0, category_flip=flip)
    assert bool(np.all(np.diff(events.ts) >= 0)) and flipped.sum() > 10
    cfg = ControllerConfig(window_seconds=120.0, decay=0.7,
                           drift_threshold=0.02, hysteresis_windows=1,
                           kmeans=KMeansConfig(k=12, seed=42),
                           scoring=validated_scoring_config())
    res = ReplicationController(manifest, cfg).run(events)
    target = np.asarray([CATEGORIES.index(PLANTED_TO_CATEGORY[flip[c]])
                         if f else -1
                         for c, f in zip(manifest.category, flipped)])
    cohort = flipped.nonzero()[0]
    match = (res.category_idx[cohort] == target[cohort]).mean()
    assert match >= 0.5, f"cohort majority not re-planned (match={match})"


def test_controller_plan_entries_export(workload):
    manifest, events = workload
    res = ReplicationController(manifest, _cfg()).run(events)
    entries = res.plan_entries()
    assert len(entries) == len(manifest)
    planned = [e for e in entries if e.category != "Unplanned"]
    assert planned
    rf_table = validated_scoring_config().replication_factors
    assert all(e.rf == rf_table[e.category] for e in planned)


def test_simulate_access_with_shift_contract():
    manifest = generate_population(GeneratorConfig(n_files=100, seed=3))
    ev1, fl1 = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=200.0, seed=4),
        shift_at=100.0, category_flip={"hot": "archival"})
    ev2, fl2 = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=200.0, seed=4),
        shift_at=100.0, category_flip={"hot": "archival"})
    np.testing.assert_array_equal(ev1.ts, ev2.ts)        # deterministic
    np.testing.assert_array_equal(ev1.path_id, ev2.path_id)
    np.testing.assert_array_equal(fl1, fl2)
    assert bool(np.all(np.diff(ev1.ts) >= 0))            # globally sorted
    want = np.asarray([c == "hot" for c in manifest.category])
    np.testing.assert_array_equal(fl1, want)
    with pytest.raises(ValueError, match="shift_at"):
        simulate_access_with_shift(
            manifest, SimulatorConfig(duration_seconds=200.0, seed=4),
            shift_at=300.0, category_flip={"hot": "archival"})


def test_control_bench_small_scenario(tmp_path):
    """The shifted-workload bench harness end to end at toy scale: both
    criteria fields present, artifact JSON round-trips, windows consistent."""
    from cdrs_tpu.benchmarks.control_bench import run_control_bench

    out = run_control_bench(n_files=150, seed=7, duration=800.0,
                            n_windows=8, k=8)
    assert set(out) == {"scenario", "controller", "baseline", "criteria"}
    c, b = out["controller"], out["baseline"]
    assert len(c["cohort_match_per_window"]) == 8
    assert len(b["bytes_migrated_per_window"]) == 8
    assert c["bytes_migrated_total"] == sum(c["bytes_migrated_per_window"])
    p = tmp_path / "cb.json"
    p.write_text(json.dumps(out))
    assert json.loads(p.read_text())["criteria"] == out["criteria"]


def test_controller_jax_backend_runs(workload):
    pytest.importorskip("jax")
    manifest, events = workload
    cfg = _cfg(backend="jax")
    res = ReplicationController(manifest, cfg).run(events)
    assert res.records and res.records[0]["recluster_mode"] == "full"
    with pytest.raises(ValueError, match="decay"):
        _cfg(backend="jax", decay=0.5)
