"""Serving layer (ISSUE 6): vectorized read router, queue model, SLO
accounting, hotspot feedback, bucketed telemetry histograms, and the
controller/CLI wiring."""

import io
import json
import os

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, evaluate_placement, \
    place_replicas
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.serve import (
    POLICIES,
    HotspotDetector,
    ReadRouter,
    ServeConfig,
    SloSpec,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))


def _placement(n_files=500, n_nodes=6, rf=3, seed=0):
    nodes = tuple(f"dn{i}" for i in range(1, n_nodes + 1))
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=nodes))
    placement = place_replicas(
        manifest, np.full(n_files, rf, dtype=np.int32),
        ClusterTopology(nodes=nodes), seed=seed)
    return manifest, placement


def _reads(n_files, n_nodes, e=20000, seed=0, span=60.0, skew=3.0):
    rng = np.random.default_rng(seed)
    ts = np.sort(rng.random(e) * span)
    pid = (n_files * rng.random(e) ** skew).astype(np.int32)
    client = rng.integers(-1, n_nodes, e).astype(np.int32)
    return ts, pid, client


# -- routing policies --------------------------------------------------------


@pytest.mark.parametrize("policy", POLICIES)
def test_never_selects_unreachable_node(policy):
    """No policy ever routes a read to a node outside the reachable set;
    reads with zero reachable replicas come back unavailable (-1)."""
    manifest, placement = _placement(seed=SEED)
    n_nodes = len(placement.topology)
    rm = placement.replica_map
    # Knock out two nodes: their slots become unreachable.
    down = {1, 4}
    node_ok = np.asarray([i not in down for i in range(n_nodes)])
    slot_ok = (rm >= 0) & node_ok[np.clip(rm, 0, None)]
    ts, pid, client = _reads(len(manifest), n_nodes, seed=SEED + 1)
    router = ReadRouter(n_nodes, ServeConfig(policy=policy, seed=SEED))
    res = router.route(rm, slot_ok, np.ones(n_nodes), ts=ts, pid=pid,
                       client=client, window_seconds=60.0)
    routed = res.server[res.server >= 0]
    assert not np.isin(routed, list(down)).any()
    # Unavailable exactly when the file has no reachable slot.
    expect_unavail = ~slot_ok[pid].any(axis=1)
    assert np.array_equal(res.server < 0, expect_unavail)
    assert res.n_unavailable == int(expect_unavail.sum())
    assert res.latency_ms.shape == (res.n_routed,)
    assert np.isfinite(res.latency_ms).all() and (res.latency_ms > 0).all()


def test_p2c_load_not_worse_than_random():
    """Power-of-two-choices' max node load <= random-replica's on the same
    seed (Mitzenmacher) — measured as busy-seconds on a skewed stream."""
    manifest, placement = _placement(n_files=300, seed=SEED)
    n_nodes = len(placement.topology)
    rm, slot_ok = placement.replica_map, placement.replica_map >= 0
    ts, pid, client = _reads(len(manifest), n_nodes, e=60000,
                             seed=SEED + 2, skew=5.0)
    client = np.full_like(client, -1)  # no local short-circuit: pure policy
    loads = {}
    for policy in ("random", "p2c"):
        router = ReadRouter(n_nodes, ServeConfig(policy=policy, seed=SEED))
        res = router.route(rm, slot_ok, np.ones(n_nodes), ts=ts, pid=pid,
                           client=client, window_seconds=60.0)
        loads[policy] = res.reads_per_node.max()
    assert loads["p2c"] <= loads["random"]


def test_flat_nominal_locality_matches_offline_replay():
    """Flat topology + all-nominal throughput: the router's locality (any
    policy — local reads always short-circuit) equals the offline
    replay's read_locality on the same placement and events."""
    manifest, placement = _placement(n_files=400, rf=2, seed=SEED)
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=300, seed=SEED + 3))
    m = evaluate_placement(manifest, events, placement, seed=0)

    from cdrs_tpu.cluster.evaluate import _client_to_topology

    keep = events.path_id >= 0
    is_read = np.asarray(events.op)[keep] == 0
    pid = events.path_id[keep][is_read]
    ts = events.ts[keep][is_read]
    client = _client_to_topology(events, placement.topology)[keep][is_read]
    n_nodes = len(placement.topology)
    for policy in ("primary", "random"):
        router = ReadRouter(n_nodes, ServeConfig(policy=policy, seed=SEED))
        res = router.route(placement.replica_map,
                           placement.replica_map >= 0, np.ones(n_nodes),
                           ts=ts, pid=pid, client=client)
        assert res.locality == pytest.approx(m.read_locality, abs=1e-12)
        assert res.n_unavailable == 0


def test_queue_model_matches_naive_fifo():
    """The closed-form latency (s(k+1) + cummax(a - sk)) equals the naive
    per-request FIFO recurrence on a single node."""
    rng = np.random.default_rng(SEED)
    ts = np.sort(rng.random(800) * 2.0)
    rm = np.zeros((50, 1), dtype=np.int32)
    router = ReadRouter(1, ServeConfig(policy="primary", service_ms=1.5))
    res = router.route(rm, rm >= 0, np.ones(1), ts=ts,
                       pid=rng.integers(0, 50, 800).astype(np.int32),
                       client=np.full(800, -1, dtype=np.int32))
    s = 1.5e-3
    f_prev = -np.inf
    naive = []
    for a in ts:
        f_prev = max(a, f_prev) + s
        naive.append((f_prev - a) * 1000.0)
    assert np.allclose(res.latency_ms, naive)
    assert res.p50_ms <= res.p95_ms <= res.p99_ms


def test_straggler_stretches_service_time():
    """A degraded node's reads take at least service_ms/factor."""
    rng = np.random.default_rng(SEED)
    e = 2000
    ts = np.sort(rng.random(e) * 60.0)
    rm = np.zeros((10, 1), dtype=np.int32)  # every read forced to node 0
    router = ReadRouter(1, ServeConfig(policy="primary", service_ms=0.5))
    thr = np.asarray([0.25])
    res = router.route(rm, rm >= 0, thr, ts=ts,
                       pid=rng.integers(0, 10, e).astype(np.int32),
                       client=np.full(e, -1, dtype=np.int32),
                       window_seconds=60.0)
    assert res.latency_ms.min() >= 2.0 - 1e-9  # 0.5ms / 0.25
    nominal = ReadRouter(1, ServeConfig(policy="primary",
                                        service_ms=0.5)).route(
        rm, rm >= 0, np.ones(1), ts=ts,
        pid=rng.integers(0, 10, e).astype(np.int32),
        client=np.full(e, -1, dtype=np.int32), window_seconds=60.0)
    assert res.p99_ms > nominal.p99_ms


def test_routing_deterministic_given_seed():
    manifest, placement = _placement(seed=SEED)
    n_nodes = len(placement.topology)
    ts, pid, client = _reads(len(manifest), n_nodes, seed=SEED)
    for policy in POLICIES:
        a, b = (ReadRouter(n_nodes, ServeConfig(policy=policy, seed=7))
                .route(placement.replica_map, placement.replica_map >= 0,
                       np.ones(n_nodes), ts=ts, pid=pid, client=client,
                       rng=np.random.default_rng([7, 3]))
                for _ in range(2))
        assert np.array_equal(a.server, b.server)
        assert np.array_equal(a.latency_ms, b.latency_ms)


def test_full_outage_window_has_no_latency_sample():
    """A window where every read is unavailable reports latency
    percentiles as None — not 0, which would claim a perfect tail for
    exactly the worst window — while still counting the unavailable
    reads (and their fraction) in the serving digest."""
    from cdrs_tpu.obs.aggregate import serve_digest

    rng = np.random.default_rng(SEED)
    rm = np.zeros((10, 1), dtype=np.int32)
    slot_ok = np.zeros((10, 1), dtype=bool)  # nothing reachable
    router = ReadRouter(1, ServeConfig(policy="p2c"))
    res = router.route(rm, slot_ok, np.ones(1),
                       ts=np.sort(rng.random(100)),
                       pid=rng.integers(0, 10, 100).astype(np.int32),
                       client=np.full(100, -1, dtype=np.int32))
    assert res.n_unavailable == 100 and res.n_routed == 0
    fields = res.record_fields()
    assert fields["latency_p99_ms"] is None
    assert fields["latency_p50_ms"] is None
    d = serve_digest([{"window": 0, **fields}])
    assert d["reads_unavailable"] == 100
    assert d["unavailable_fraction"] == 1.0
    assert d["latency_p99_ms_max"] is None
    # The renderers survive the latency-less digest.
    from cdrs_tpu.obs.metrics_cli import summarize_events
    from cdrs_tpu.obs.report import render_html

    out = io.StringIO()
    summarize_events([{"kind": "window", "window": 0, **fields}], out=out)
    assert "p99 — ms" in out.getvalue()
    assert "Serving (read-path SLO)" in render_html(
        [{"kind": "window", "window": 0, **fields}])


def test_slo_burn_accounting():
    """Burn = (over-target + unavailable) / reads / error budget."""
    rng = np.random.default_rng(SEED)
    e = 4000
    ts = np.sort(rng.random(e) * 1.0)  # 4000 r/s on one 2000 r/s node
    rm = np.zeros((10, 1), dtype=np.int32)
    router = ReadRouter(1, ServeConfig(
        policy="primary", service_ms=0.5,
        slo=SloSpec(target_ms=5.0, availability=0.99)))
    res = router.route(rm, rm >= 0, np.ones(1), ts=ts,
                       pid=rng.integers(0, 10, e).astype(np.int32),
                       client=np.full(e, -1, dtype=np.int32),
                       window_seconds=1.0)
    over = int((res.latency_ms > 5.0).sum())
    assert res.slo_violations == over
    assert res.slo_burn == pytest.approx((over / e) / 0.01)
    assert res.slo_burn > 1.0  # an overloaded node burns the budget


# -- hotspot detector --------------------------------------------------------


def test_hotspot_detects_spike_not_stationary():
    det = HotspotDetector(100, spike_factor=4.0, min_reads=20, top_k=4)
    rng = np.random.default_rng(SEED)
    base = rng.poisson(10.0, 100).astype(float)
    assert not det.observe(base).fired          # first window: baseline
    for _ in range(3):
        assert not det.observe(
            rng.poisson(10.0, 100).astype(float)).fired
    spike = rng.poisson(10.0, 100).astype(float)
    spike[[7, 42]] += 200.0
    res = det.observe(spike)
    assert res.fired and set(res.files) == {7, 42}
    assert res.score >= 4.0
    # The spike folds into the EWMA: a repeat at the same level decays.
    res2 = det.observe(spike)
    assert res2.score < res.score


def test_hotspot_deterministic_and_seed_invariant():
    """Detection is pure arithmetic on counts: identical across detector
    instances and independent of any router seed."""
    rng = np.random.default_rng(SEED)
    windows = [rng.poisson(8.0, 64).astype(float) for _ in range(6)]
    windows[4][5] += 500.0

    def run():
        det = HotspotDetector(64, min_reads=10)
        return [(r.fired, r.score, r.files)
                for r in (det.observe(w) for w in windows)]

    assert run() == run()


def test_hotspot_state_roundtrip():
    det = HotspotDetector(32, alpha=0.5)
    det.observe(np.arange(32, dtype=float))
    det.observe(np.ones(32))
    arrays = det.state_arrays()
    det2 = HotspotDetector(32, alpha=0.5)
    det2.load_state_arrays(arrays)
    a = det.observe(np.full(32, 7.0))
    b = det2.observe(np.full(32, 7.0))
    assert (a.fired, a.score, a.files) == (b.fired, b.score, b.files)
    assert np.array_equal(det.ewma, det2.ewma)


# -- controller integration --------------------------------------------------


_NODES5 = ("dn1", "dn2", "dn3", "dn4", "dn5")


def _controller(manifest, serve=None, faults=None, **kw):
    cfg = ControllerConfig(
        window_seconds=60.0, default_rf=2,
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(), serve=serve,
        fault_schedule=faults, **kw)
    return ReplicationController(manifest, cfg)


@pytest.fixture(scope="module")
def serve_workload():
    manifest = generate_population(
        GeneratorConfig(n_files=300, seed=SEED + 5, nodes=_NODES5))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=480, seed=SEED + 6))
    return manifest, events


def test_controller_serve_records(serve_workload):
    manifest, events = serve_workload
    res = _controller(manifest, serve=ServeConfig(policy="p2c",
                                                  seed=3)).run(events)
    busy = [r for r in res.records if r["n_events"]]
    assert busy
    for r in busy:
        assert r["reads_routed"] + r["reads_unavailable"] == r["n_reads"]
        assert np.isfinite(r["latency_p99_ms"])
        assert r["latency_p50_ms"] <= r["latency_p99_ms"]
        assert 0.0 <= r["serve_locality"] <= 1.0
        assert r["utilization_max"] >= 0.0
    summary = res.summary()
    assert summary["serve"]["reads_routed"] == sum(
        r["reads_routed"] for r in busy)
    assert np.isfinite(summary["serve"]["latency_p99_ms_max"])


def test_controller_serve_kill_resume_bit_identical(serve_workload, tmp_path):
    """Serve state (hotspot EWMA, per-window routing seeds) rides the npz
    checkpoint: kill/resume reproduces the uninterrupted records."""
    manifest, events = serve_workload
    from cdrs_tpu.faults import FaultSchedule

    sched = FaultSchedule.from_specs(
        ["partition:dn2@3-5", "degrade:dn3@2-6:0.25"])

    def mk():
        return _controller(manifest, serve=ServeConfig(policy="p2c", seed=3),
                           faults=FaultSchedule(sched.events))

    def strip(rs):
        return [{k: v for k, v in r.items() if k != "seconds"}
                for r in rs]

    full = mk().run(events)
    ck = str(tmp_path / "serve.npz")
    a = mk().run(events, checkpoint_path=ck, max_windows=4)
    b = mk().run(events, checkpoint_path=ck)
    assert strip(a.records) + strip(b.records) == strip(full.records)
    assert np.array_equal(b.rf, full.rf)


def test_serve_checkpoint_flag_mismatch(serve_workload, tmp_path):
    manifest, events = serve_workload
    ck = str(tmp_path / "plain.npz")
    _controller(manifest).run(events, checkpoint_path=ck, max_windows=2)
    with pytest.raises(ValueError, match="serve"):
        _controller(manifest, serve=ServeConfig()).run(
            events, checkpoint_path=ck)


def test_hotspot_triggers_recluster_drift_does_not():
    """Flash crowd: the drift-only controller sleeps through the burst
    (score inside the detector's noise band); the serve-enabled one
    re-clusters the burst window with trigger='hotspot' and raises the
    audit flag.  Runs the bench's own scenario (benchmarks/serve_bench)
    at its quick scale — the acceptance criterion, tested."""
    from cdrs_tpu.benchmarks.serve_bench import run_flash_crowd

    f = run_flash_crowd(n_files=200, duration=900.0, n_windows=9,
                        burst_windows=(6, 6), k=8)
    assert f["hotspot_catches_what_drift_misses"]
    assert f["drift_only"]["reclusters_at_or_after_burst"] == []
    hot = f["hotspot_feedback"]["hotspot_reclusters"]
    assert hot == [6]
    assert f["hotspot_feedback"]["audit_hotspot_flag_windows"] == hot
    # The separation the artifact pins: the burst barely moves the drift
    # statistic but multiplies the hotspot ratio far past its threshold.
    assert f["drift_at_burst"] < f["drift_threshold"]
    assert f["hotspot_score_at_burst"] >= 4.0


# -- telemetry: bucketed histograms & raw cap --------------------------------


def test_histogram_bulk_buckets_and_merge():
    from cdrs_tpu.obs import JsonlSink, Telemetry, read_events
    from cdrs_tpu.obs.aggregate import bucket_percentile, collect
    import tempfile

    rng = np.random.default_rng(SEED)
    vals = rng.lognormal(0.0, 1.0, 5000)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        with Telemetry(JsonlSink(path)) as tel:
            tel.histogram_bulk("lat", vals[:3000])
            tel.histogram_bulk("lat", vals[3000:])
            agg_mem = tel.hist_buckets["lat"]
        events = read_events(path)
    bulk = [e for e in events if e.get("kind") == "hist_bulk"]
    assert len(bulk) == 2  # one event per CALL, not per sample
    digest = collect(events)
    agg = digest["hist_buckets"]["lat"]
    assert agg["count"] == 5000 == sum(agg["buckets"].values())
    assert agg["count"] == agg_mem["count"]
    assert agg["min"] == pytest.approx(vals.min())
    assert agg["max"] == pytest.approx(vals.max())
    # Bucket-estimated percentiles sit within one ladder step of exact.
    for q in (0.5, 0.95, 0.99):
        est = bucket_percentile(agg, q)
        exact = float(np.quantile(vals, q))
        assert exact <= est <= exact * 10 ** 0.25 * 1.01


def test_histogram_bulk_subsample_scaling():
    from cdrs_tpu.obs.telemetry import HIST_BULK_SAMPLE_CAP, bucket_counts

    rng = np.random.default_rng(SEED)
    vals = rng.lognormal(0.0, 1.0, HIST_BULK_SAMPLE_CAP * 3 + 17)
    sparse, n, total, vmin, vmax = bucket_counts(vals)
    assert n == sum(c for _, c in sparse)  # counts stay self-consistent
    assert abs(n - vals.size) <= 4  # stride rounding only
    assert vmin == vals.min() and vmax == vals.max()
    assert total == pytest.approx(vals.sum(), rel=0.05)


def test_histogram_raw_cap_keeps_percentiles():
    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.obs.aggregate import percentile
    from cdrs_tpu.obs.telemetry import HIST_RAW_CAP

    with Telemetry() as tel:
        n = HIST_RAW_CAP * 6
        for i in range(n):
            tel.histogram("h", float(i % 1000))
        kept = tel.histograms["h"]
    assert len(kept) < HIST_RAW_CAP
    assert percentile(kept, 0.5) == pytest.approx(500, rel=0.05)
    assert percentile(kept, 0.95) == pytest.approx(950, rel=0.05)


def test_prometheus_histogram_export():
    from cdrs_tpu.obs.metrics_cli import prometheus_lines

    events = [
        {"kind": "hist_bulk", "name": "serve.latency_ms", "count": 7,
         "sum": 10.0, "min": 0.4, "max": 900.0,
         "buckets": [[0.5623413251903491, 3], [1.0, 3], ["+Inf", 1]]},
    ]
    lines = prometheus_lines(events)
    text = "\n".join(lines)
    assert "# TYPE cdrs_serve_latency_ms histogram" in text
    assert 'cdrs_serve_latency_ms_bucket{le="1"} 6' in text  # cumulative
    assert 'cdrs_serve_latency_ms_bucket{le="+Inf"} 7' in text
    assert "cdrs_serve_latency_ms_count 7" in text


# -- digests -----------------------------------------------------------------


def _serve_windows():
    return [{"kind": "window", "window": i, "n_events": 100, "n_reads": 80,
             "reads_routed": 78, "reads_unavailable": 2,
             "latency_p50_ms": 0.5, "latency_p95_ms": 1.0,
             "latency_p99_ms": 2.0 + i, "slo_burn": 0.5 * i,
             "utilization_max": 0.5, "serve_locality": 0.7,
             "hotspot_files": [1] if i == 1 else [],
             "recluster_trigger": "hotspot" if i == 1 else None}
            for i in range(3)]


def test_summarize_serving_and_unavailable_fraction(capsys):
    from cdrs_tpu.obs.metrics_cli import summarize_events

    events = _serve_windows()
    events.append({"kind": "window", "window": 3, "n_events": 50,
                   "n_reads": 40, "unavailable_reads": 4,
                   "durability": {"lost": 1, "at_risk": 0,
                                  "under_replicated": 0, "nodes_up": 4}})
    out = io.StringIO()
    summarize_events(events, out=out)
    text = out.getvalue()
    assert "Serving: 234 reads routed over 3 windows" in text
    assert "hotspots: 1 windows fired" in text
    # unavailable fraction normalizes by presented reads: 4 / 280.
    assert "fraction 0.01429" in text


def test_report_serving_section():
    from cdrs_tpu.obs.report import render_html

    html = render_html(_serve_windows())
    assert "Serving (read-path SLO)" in html
    assert "hotspot-triggered" in html


def test_serve_digest_absent_for_plain_streams():
    from cdrs_tpu.obs.aggregate import serve_digest

    assert serve_digest([{"window": 0, "n_events": 5}]) is None


# -- regress ingestion -------------------------------------------------------


def test_regress_extracts_bench_records():
    from cdrs_tpu.benchmarks.regress import extract_records

    doc = {"criteria": {}, "bench_records": [
        {"metric": "serve_routed_reads_per_sec", "value": 2.0e6,
         "unit": "reads/s", "backend": "numpy"},
        {"metric": "serve_chaos_p99_ms_p2c", "value": 8.0, "unit": "ms",
         "backend": "numpy"},
    ]}
    recs = extract_records(doc, "serve_bench.json")
    assert {r["metric"] for r in recs} == {
        "serve_routed_reads_per_sec", "serve_chaos_p99_ms_p2c"}
    by = {r["metric"]: r for r in recs}
    assert by["serve_routed_reads_per_sec"]["direction"] == "higher"
    assert by["serve_chaos_p99_ms_p2c"]["direction"] == "lower"
    assert by["serve_chaos_p99_ms_p2c"]["platform"] == "numpy"


# -- CLI ---------------------------------------------------------------------


def test_serve_cli_smoke(tmp_path, capsys):
    from cdrs_tpu.cli import main

    manifest = generate_population(
        GeneratorConfig(n_files=120, seed=11, nodes=("dn1", "dn2", "dn3")))
    man_path = str(tmp_path / "m.csv")
    manifest.write_csv(man_path)
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=240, seed=12))
    log_path = str(tmp_path / "a.log")
    events.write_csv(log_path, manifest)
    metrics = str(tmp_path / "s.jsonl")
    rc = main(["serve", "--manifest", man_path, "--access_log", log_path,
               "--policy", "p2c", "--degrade", "dn2@1-2:0.5",
               "--metrics", metrics])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["reads_routed"] > 0
    assert np.isfinite(out["latency_p99_ms_last"])
    assert out["policy"] == "p2c"
    from cdrs_tpu.obs import read_events
    from cdrs_tpu.obs.aggregate import collect, serve_digest

    stream = read_events(metrics)
    digest = collect(stream)
    assert "serve.latency_ms" in digest["hist_buckets"]
    assert serve_digest(digest["windows"]) is not None


def test_serve_config_validation():
    with pytest.raises(ValueError, match="policy"):
        ServeConfig(policy="nearest")
    with pytest.raises(ValueError, match="service_ms"):
        ServeConfig(service_ms=0.0)
    with pytest.raises(ValueError, match="availability"):
        SloSpec(availability=1.0)
    with pytest.raises(ValueError, match="spike_factor"):
        ServeConfig(hotspot_spike_factor=1.0)
