"""The one-command benchmark sweep (cdrs_tpu.benchmarks.summary).

Unit-level: run_bench/bench_ingest are stubbed so no real benchmark runs —
the real sweep is exercised on the chip (data/bench_sweep_r4.json).  What
must hold structurally: every config lands under the right key, a failing
step records its error instead of aborting the sweep, and --out writes
valid JSON.
"""

import json

import numpy as np  # noqa: F401  (jax-optional module gate parity)
import pytest

pytest.importorskip("jax")

import cdrs_tpu.benchmarks.harness as harness
import cdrs_tpu.benchmarks.ingest as ingest_mod
from cdrs_tpu.benchmarks.summary import main, run_summary


@pytest.fixture
def stubbed(monkeypatch):
    calls = []

    def fake_run_bench(config=2, **kw):
        calls.append((config, kw))
        if kw.get("dtype") == "bfloat16":
            raise RuntimeError("no bf16 today")
        return {"config": config, "value": float(config), **kw}

    monkeypatch.setattr(harness, "run_bench", fake_run_bench)
    monkeypatch.setattr(ingest_mod, "bench_ingest",
                        lambda: {"value": 123.0, "unit": "row/s"})
    return calls


def test_run_summary_structure_and_fault_isolation(stubbed):
    out = run_summary(quality=False)
    assert set(out) >= {"hardware", "lloyd", "e2e", "streaming", "ingestion"}
    assert out["lloyd"]["config2"]["value"] == 2.0
    assert out["lloyd"]["config2_matmul"]["update"] == "matmul"
    # the bf16 step failed — recorded, not fatal, and the sweep continued
    assert "no bf16 today" in out["lloyd"]["config4_bf16"]["error"]
    assert out["streaming"]["config"] == 5
    assert {f"config{c}" for c in (2, 3, 4)} <= set(out["e2e"])
    assert all(v["e2e"] for v in out["e2e"].values())
    assert out["ingestion"]["value"] == 123.0


def test_summary_cli_writes_json(stubbed, tmp_path, capsys):
    out_path = tmp_path / "sweep.json"
    assert main(["--out", str(out_path), "--no_quality"]) == 0
    on_disk = json.loads(out_path.read_text())
    assert on_disk["lloyd"]["config1"]["value"] == 1.0
    # stdout carries the same JSON
    assert json.loads(capsys.readouterr().out)["lloyd"]["config1"]["value"] == 1.0


def test_step_error_isolation_unit(capsys):
    """_step in isolation: result lands under the key on success, the
    error string (with the exception type) replaces it on failure, and
    the failure never propagates."""
    from cdrs_tpu.benchmarks.summary import _step

    out = {}
    _step(out, "ok", lambda: {"v": 1})
    _step(out, "boom", lambda: (_ for _ in ()).throw(KeyError("nope")))
    assert out["ok"] == {"v": 1}
    assert out["boom"]["error"].startswith("KeyError")
    assert "boom FAILED" in capsys.readouterr().err


def test_telemetry_overhead_structure():
    """The ISSUE-2 overhead record at toy scale: all fields present and
    internally consistent.  The ≤5% budget itself is asserted by the real
    sweep on the bench host, not CI-timed — here only the bookkeeping."""
    from cdrs_tpu.benchmarks.summary import telemetry_overhead

    out = telemetry_overhead(n_files=300, duration=60.0, repeats=1)
    assert set(out) >= {"plain_seconds", "telemetry_seconds",
                        "overhead_ratio", "within_budget", "budget",
                        "events_emitted"}
    assert out["plain_seconds"] > 0 and out["telemetry_seconds"] > 0
    assert out["overhead_ratio"] == pytest.approx(
        out["telemetry_seconds"] / out["plain_seconds"])
    assert out["events_emitted"] > 0  # spans + kmeans trace landed
    assert out["within_budget"] == (out["overhead_ratio"] <= out["budget"])
