"""Native C++ runtime components (native/cdrs_native.cpp via ctypes).

Tests skip when the library cannot be built (no g++/make on the host).
"""

import os
import tempfile

import numpy as np
import pytest

from cdrs_tpu.runtime.native import (
    native_available,
    parse_log_chunk_native,
    simulate_events_native,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable (g++/make?)")


def test_simulator_schema_and_determinism():
    n = 200
    rng = np.random.default_rng(0)
    read = rng.uniform(0.1, 1.0, n)
    write = rng.uniform(0.0, 0.3, n)
    loc = rng.uniform(0.0, 1.0, n)
    prim = rng.integers(0, 3, n).astype(np.int32)
    pool = np.arange(4, dtype=np.int32)

    ts, pid, op, cl = simulate_events_native(read, write, loc, prim, pool,
                                             duration=120.0, sim_start=1.7e9,
                                             seed=7)
    assert (np.diff(ts) >= 0).all()           # globally time-sorted
    assert ts.min() >= 1.7e9 and ts.max() < 1.7e9 + 120.0
    assert set(np.unique(op)) <= {0, 1}
    assert pid.min() >= 0 and pid.max() < n
    assert cl.min() >= 0 and cl.max() < 4

    # Deterministic across thread counts (per-file seeded RNG).
    ts2, pid2, op2, cl2 = simulate_events_native(
        read, write, loc, prim, pool, 120.0, 1.7e9, seed=7, n_threads=3)
    assert (ts == ts2).all() and (pid == pid2).all()
    assert (op == op2).all() and (cl == cl2).all()


def test_simulator_rate_statistics():
    """Event counts and op mix must track the Poisson parameters."""
    n = 500
    read = np.full(n, 0.8)
    write = np.full(n, 0.2)
    loc = np.full(n, 1.0)   # always primary
    prim = np.full(n, 2, dtype=np.int32)
    pool = np.arange(4, dtype=np.int32)
    T = 200.0
    ts, pid, op, cl = simulate_events_native(read, write, loc, prim, pool,
                                             T, 0.0, seed=1)
    expected = n * 1.0 * T
    assert abs(len(ts) - expected) < 5 * np.sqrt(expected)
    assert abs(float((op == 1).mean()) - 0.2) < 0.01
    assert (cl == 2).all()  # locality 1.0 -> always the primary node


def test_log_parser_matches_python_reader():
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.io.events import EventLog
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    m = generate_population(GeneratorConfig(n_files=60, seed=5))
    ev = simulate_access(m, SimulatorConfig(duration_seconds=45.0, seed=6))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "access.log")
        ev.write_csv(p, m)
        py = EventLog.read_csv(p, m, native=False)
        nat = EventLog.read_csv(p, m, native=True)
    np.testing.assert_allclose(nat.ts, py.ts, atol=1e-9)
    assert (nat.path_id == py.path_id).all()
    assert (nat.op == py.op).all()
    assert [nat.clients[i] for i in nat.client_id] == \
           [py.clients[i] for i in py.client_id]


def test_log_parser_quoted_csv_falls_back():
    """Quoted rows (comma in path) must not silently mis-parse: the native
    scanner bails and the python csv reader handles them."""
    from cdrs_tpu.io.events import EventLog, Manifest

    m = Manifest(paths=["/a,b.bin"], creation_ts=np.array([0.0]),
                 primary_node_id=np.array([0], dtype=np.int32),
                 size_bytes=np.array([1], dtype=np.int64),
                 category=["hot"], nodes=["dn1"])
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "access.log")
        with open(p, "w") as f:
            f.write('2026-01-01T00:00:00.000Z,"/a,b.bin",READ,dn1,1000\n')
        # the chunked parser refuses quoted csv (python resumes at byte 0)
        assert parse_log_chunk_native(p, 0, 100) is None
        ev = EventLog.read_csv(p, m)  # auto-falls back to python
    assert len(ev) == 1 and ev.path_id[0] == 0


def test_native_engine_via_simulate_access():
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    m = generate_population(GeneratorConfig(n_files=50, seed=2))
    ev = simulate_access(m, SimulatorConfig(duration_seconds=30.0, seed=3),
                         engine="native")
    assert len(ev) > 0
    assert (np.diff(ev.ts) >= 0).all()
    # client ids must be valid indices into the shared vocabulary
    assert ev.client_id.max() < len(ev.clients)


def test_parse_iso_timezone_offsets(tmp_path):
    """Offset-bearing timestamps must match Python's fromisoformat epoch."""
    from cdrs_tpu.io.events import parse_iso_ts

    rows = [
        "2026-01-01T05:30:00.000+05:30,/f,READ,dn1,1",
        "2026-01-01T00:00:00.250Z,/f,WRITE,dn1,2",
        "2025-12-31T19:00:00-05:00,/f,READ,dn1,3",
    ]
    p = tmp_path / "tz.log"
    p.write_text("\n".join(rows) + "\n")
    parsed = parse_log_chunk_native(str(p), 0, 100)
    assert parsed is not None
    ts = parsed[0]
    want = [parse_iso_ts(r.split(",")[0]) for r in rows]
    np.testing.assert_allclose(ts, want, atol=1e-9)


def test_malformed_rows_fall_back(tmp_path):
    """Short/garbled rows make the native scanner bail (python path raises)."""
    p = tmp_path / "bad.log"
    p.write_text("2026-01-01T00:00:00.000Z,/f,READ\n")  # only 3 fields
    assert parse_log_chunk_native(str(p), 0, 100) is None


# ---------------------------------------------------------------------------
# Chunked ingestion + native interning (VERDICT r2 #4)
# ---------------------------------------------------------------------------


def _make_workload(tmp_path, n_files=40, duration=120.0, seed=5):
    from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=n_files, seed=seed))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=duration, seed=seed + 1))
    log = tmp_path / "access.log"
    events.write_csv(str(log), manifest)
    return manifest, str(log)


def _assert_logs_equal(a, b):
    np.testing.assert_allclose(a.ts, b.ts, atol=1e-6)
    np.testing.assert_array_equal(a.path_id, b.path_id)
    np.testing.assert_array_equal(a.op, b.op)
    np.testing.assert_array_equal(a.client_id, b.client_id)
    assert a.clients == b.clients


@pytest.mark.parametrize("batch_size", [None, 97, 1000])
def test_chunked_native_batches_match_python(tmp_path, batch_size):
    """Native chunked ingestion is byte-exact with the python csv path,
    including client-vocabulary growth order, at any batch size."""
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path)
    nat = list(EventLog.read_csv_batches(log, manifest, batch_size=batch_size,
                                         native=True))
    py = list(EventLog.read_csv_batches(log, manifest, batch_size=batch_size,
                                        native=False))
    assert sum(len(b) for b in nat) == sum(len(b) for b in py) > 0
    # Concatenated streams are identical (native chunking may split
    # batch_size=None into internal chunks).
    def cat(batches):
        return (np.concatenate([b.ts for b in batches]),
                np.concatenate([b.path_id for b in batches]),
                np.concatenate([b.op for b in batches]),
                np.concatenate([b.client_id for b in batches]),
                batches[-1].clients)
    for x, y in zip(cat(nat), cat(py)):
        if isinstance(x, list):
            assert x == y
        else:
            np.testing.assert_array_equal(np.asarray(x, np.float64),
                                          np.asarray(y, np.float64))


def test_chunked_read_csv_equals_python(tmp_path):
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path, n_files=17, duration=60.0)
    _assert_logs_equal(EventLog.read_csv(log, manifest, native=True),
                       EventLog.read_csv(log, manifest, native=False))


def test_chunked_falls_back_mid_stream_on_quoting(tmp_path):
    """A quoted row mid-file hands over to the python parser at that byte —
    nothing is lost or duplicated."""
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path, n_files=8, duration=30.0)
    with open(log) as f:
        lines = f.read().splitlines()
    assert len(lines) > 10
    # Quote a client field halfway through the file.
    mid = len(lines) // 2
    parts = lines[mid].split(",")
    parts[3] = f'"{parts[3]}"'
    lines[mid] = ",".join(parts)
    with open(log, "w") as f:
        f.write("\n".join(lines) + "\n")

    nat = EventLog.read_csv(log, manifest, native=True)
    py = EventLog.read_csv(log, manifest, native=False)
    _assert_logs_equal(nat, py)


def test_intern_map_lookup(tmp_path):
    from cdrs_tpu.runtime.native import InternMap, _strings_to_blob

    m = InternMap(["/a", "/bb", "/ccc"])
    blob, off = _strings_to_blob(["/bb", "/zz", "/a", "/ccc", "/a"])
    np.testing.assert_array_equal(m.lookup(blob, off), [1, -1, 0, 2, 0])


def test_unknown_paths_get_minus_one(tmp_path):
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path, n_files=6, duration=30.0)
    with open(log, "a") as f:
        f.write("2026-01-01T00:00:00.000Z,/not/in/manifest,READ,dn1,77\n")
    nat = EventLog.read_csv(log, manifest, native=True)
    py = EventLog.read_csv(log, manifest, native=False)
    _assert_logs_equal(nat, py)
    assert (nat.path_id == -1).sum() == 1


# ---------------------------------------------------------------------------
# Property test: native ingestion == python ingestion on adversarial logs
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:
    _name = st.text(
        alphabet=st.characters(
            codec="utf-8",
            # no newlines/CR (CSV rows), no NUL; commas/quotes INCLUDED so
            # some rows force the quoted-csv python fallback mid-stream
            exclude_characters="\n\r\x00"),
        min_size=1, max_size=20)

    @settings(max_examples=25, deadline=None)
    @given(st.data())
    def test_ingestion_parity_fuzz(tmp_path_factory, data):
        import csv as _csv

        from cdrs_tpu.io.events import EventLog, Manifest

        n_files = data.draw(st.integers(1, 8))
        paths = data.draw(st.lists(_name, min_size=n_files, max_size=n_files,
                                   unique=True))
        nodes = ["dn1", "dn2"]
        m = Manifest(paths=paths, creation_ts=np.zeros(n_files),
                     primary_node_id=np.zeros(n_files, dtype=np.int32),
                     size_bytes=np.ones(n_files, dtype=np.int64),
                     category=["moderate"] * n_files, nodes=nodes)

        n_rows = data.draw(st.integers(0, 30))
        rows = []
        for _ in range(n_rows):
            ts = 1.7e9 + data.draw(st.floats(0, 1e6, allow_nan=False))
            path = data.draw(st.one_of(st.sampled_from(paths), _name))
            op = data.draw(st.sampled_from(["READ", "WRITE"]))
            client = data.draw(st.one_of(st.sampled_from(nodes), _name))
            from datetime import datetime, timezone
            iso = datetime.fromtimestamp(ts, tz=timezone.utc).strftime(
                "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
            rows.append([iso, path, op, client, "1000"])

        d = tmp_path_factory.mktemp("fuzz")
        log = os.path.join(str(d), "access.log")
        with open(log, "w", newline="") as f:
            w = _csv.writer(f)
            for r in rows:
                w.writerow(r)

        nat = EventLog.read_csv(log, m, native=True)
        py = EventLog.read_csv(log, m, native=False)
        np.testing.assert_allclose(nat.ts, py.ts, atol=1e-6)
        np.testing.assert_array_equal(nat.path_id, py.path_id)
        np.testing.assert_array_equal(nat.op, py.op)
        np.testing.assert_array_equal(nat.client_id, py.client_id)
        assert nat.clients == py.clients


def test_native_log_writer_roundtrip(tmp_path):
    """Native writer -> native reader -> identical EventLog; and byte-level
    parity of the timestamp format with the python writer."""
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path, n_files=20, duration=60.0)
    ev = EventLog.read_csv(log, manifest)
    # write via native (default), re-read, compare
    log2 = str(tmp_path / "rewritten.log")
    ev.write_csv(log2, manifest)
    ev2 = EventLog.read_csv(log2, manifest)
    np.testing.assert_allclose(ev2.ts, ev.ts, atol=2e-3)  # ms truncation
    np.testing.assert_array_equal(ev2.path_id, ev.path_id)
    np.testing.assert_array_equal(ev2.op, ev.op)
    np.testing.assert_array_equal(ev2.client_id, ev.client_id)


def test_intern_build_ids_are_positions_with_duplicates():
    """intern_build ids are input POSITIONS even with duplicate strings: a
    duplicate resolves to its first position, later uniques keep their own
    position, and the size/export cover all n entries (the unordered_map
    emplace semantics the open-addressing table replaced)."""
    from cdrs_tpu.runtime.native import InternMap, _strings_to_blob, \
        native_available

    if not native_available():
        pytest.skip("native library unavailable")
    m = InternMap(["/a", "/b", "/a", "/c"])
    assert len(m) == 4
    blob, off = _strings_to_blob(["/c", "/a", "/b", "/zzz"])
    np.testing.assert_array_equal(m.lookup(blob, off), [3, 0, 1, -1])
    assert m.names_from(0) == ["/a", "/b", "/a", "/c"]


def test_ingest_blank_lines_then_oversized_row(tmp_path):
    """rows==0 with next_offset advanced is NOT EOF: a chunk that consumes
    only blank lines and then stops on a row bigger than the native blob
    caps must hand the remainder to the python parser instead of silently
    dropping it (ADVICE r3)."""
    from cdrs_tpu.io.events import EventLog, Manifest

    big_path = "/synth/" + "x" * 1500 + ".bin"   # > 4-row chunk blob cap
    m = Manifest(paths=[big_path, "/synth/a.bin"],
                 creation_ts=np.zeros(2),
                 primary_node_id=np.zeros(2, dtype=np.int32),
                 size_bytes=np.ones(2, dtype=np.int64),
                 category=["hot", "hot"], nodes=["dn1"])
    log = str(tmp_path / "access.log")
    with open(log, "w") as f:
        f.write("\n\n")
        f.write(f"2026-01-01T00:00:00.000Z,{big_path},READ,dn1,1000\n")
        f.write("2026-01-01T00:00:01.000Z,/synth/a.bin,WRITE,dn1,1001\n")
    batches = list(EventLog.read_csv_batches(log, m, batch_size=4))
    ev = batches[0]
    assert len(ev) == 2
    np.testing.assert_array_equal(ev.path_id, [0, 1])
    np.testing.assert_array_equal(ev.op, [0, 1])


def test_native_python_writer_byte_parity(tmp_path, monkeypatch):
    """Native and python log writers emit byte-identical files: both truncate
    the millisecond field as (t - floor(t)) * 1000.0 with the same IEEE
    double ops (ADVICE r3 — the native writer used to round)."""
    from cdrs_tpu.io.events import EventLog

    manifest, log = _make_workload(tmp_path, n_files=20, duration=60.0)
    ev = EventLog.read_csv(log, manifest)
    # Append adversarial fractional seconds right at ms boundaries, plus an
    # INVALID row (path_id=-1): both writers must skip it without it shifting
    # the synthetic pid/tag column of the rows that follow.
    extra = np.array([1.7e9 + 0.0005, 1.7e9 + 0.9995, 1.7e9 + 0.123999,
                      1.7e9 + 0.5])
    ev = EventLog(
        ts=np.concatenate([ev.ts, extra]),
        path_id=np.concatenate(
            [ev.path_id, np.array([0, -1, 0, 0], np.int32)]),
        op=np.concatenate([ev.op, np.zeros(4, np.int8)]),
        client_id=np.concatenate([ev.client_id, np.zeros(4, np.int32)]),
        clients=ev.clients)
    p_nat = str(tmp_path / "nat.log")
    ev.write_csv(p_nat, manifest)
    from cdrs_tpu.runtime import native as native_mod
    monkeypatch.setattr(native_mod, "native_available", lambda: False)
    p_py = str(tmp_path / "py.log")
    ev.write_csv(p_py, manifest)
    with open(p_nat, "rb") as a, open(p_py, "rb") as b:
        assert a.read() == b.read()


def test_native_writer_quoting_fallback(tmp_path):
    """Paths needing CSV quoting route to the python csv writer."""
    from cdrs_tpu.io.events import EventLog, Manifest

    m = Manifest(paths=["/a,b.bin"], creation_ts=np.array([0.0]),
                 primary_node_id=np.array([0], dtype=np.int32),
                 size_bytes=np.array([1], dtype=np.int64),
                 category=["hot"], nodes=["dn1"])
    ev = EventLog(ts=np.array([1.7e9]), path_id=np.array([0], dtype=np.int32),
                  op=np.array([0], dtype=np.int8),
                  client_id=np.array([0], dtype=np.int32), clients=["dn1"])
    p = str(tmp_path / "quoted.log")
    ev.write_csv(p, m)
    txt = open(p).read()
    assert '"/a,b.bin"' in txt       # properly quoted
    ev2 = EventLog.read_csv(p, m)    # and re-ingestable
    assert len(ev2) == 1 and ev2.path_id[0] == 0
