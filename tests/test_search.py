"""Coverage-guided failure-space search (cdrs_tpu/scenarios/search.py):
fault-schedule edit API round-trips, mutation determinism, coverage
fingerprints, the ddmin shrinker oracle (designed-bad cell with a known
2-event minimal cause), search-loop smoke + corpus banking, distill
determinism, and the CLI surfaces (``scenarios search``, ``run --spec``
file paths)."""

import contextlib
import io
import json
import os

import numpy as np
import pytest

from cdrs_tpu.cli import main as cli_main
from cdrs_tpu.faults import FaultEvent, FaultSchedule
from cdrs_tpu.obs.aggregate import cells_digest, coverage_fingerprint
from cdrs_tpu.scenarios import (
    PRESETS,
    ScenarioSpec,
    distill_corpus,
    mutate_spec,
    preset,
    run_cell,
    run_search,
    shrink_cell,
)
from cdrs_tpu.scenarios.search import (
    RESERVED_NAME_PREFIXES,
    load_corpus,
    planted_violation_spec,
    search_cell_name,
)

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))


# -- fault-schedule edit API (events / from_events) --------------------------

def test_events_roundtrip_property():
    """Lossless decomposition/recomposition on seeds 0/1/2: events() ->
    from_events is identity, and the JSON dict form round-trips too."""
    nodes = [f"dn{i}" for i in range(1, 6)]
    for seed in (0, 1, 2):
        s = FaultSchedule.random(nodes, n_windows=12, seed=seed)
        specs = [e.spec() for e in s]
        assert specs, "random schedule should not be empty"
        back = FaultSchedule.from_events(s.events())
        assert [e.spec() for e in back] == specs
        via_json = FaultSchedule.from_events(s.to_json())
        assert [e.spec() for e in via_json] == specs
        assert via_json.to_json() == s.to_json()


def test_events_view_is_tuple_and_callable():
    s = FaultSchedule.from_specs(["crash:dn1@2", "recover:dn1@4"])
    # Back-compat: .events still behaves as the tuple attribute it was.
    assert isinstance(s.events, tuple)
    assert len(s.events) == 2
    # New surface: calling it yields an independent mutable list.
    rows = s.events()
    assert isinstance(rows, list)
    assert rows == list(s.events)
    rows.pop()
    assert len(s.events) == 2


def test_schedule_edit_constructors():
    s = FaultSchedule.from_specs(["crash:dn1@2", "crash:dn2@5"])
    assert [e.spec() for e in s.drop(0)] == ["crash:dn2@5"]
    assert [e.spec() for e in s.retime(1, 7)] == ["crash:dn1@2",
                                                  "crash:dn2@7"]
    spliced = s.splice(FaultEvent(window=3, kind="crash", node="dn3"))
    assert "crash:dn3@3" in [e.spec() for e in spliced]
    assert [e.spec() for e in s.mutate(0, node="dn4")] == \
        ["crash:dn4@2", "crash:dn2@5"]


# -- mutation ----------------------------------------------------------------

def test_mutate_spec_deterministic_and_valid():
    parent = preset("chaos-kill")
    a = mutate_spec(parent, np.random.default_rng([SEED, 7]), n_ops=2)
    b = mutate_spec(parent, np.random.default_rng([SEED, 7]), n_ops=2)
    assert a is not None and b is not None
    assert a[0].to_dict() == b[0].to_dict()
    assert a[1] == b[1] and len(a[1]) >= 1
    # Every mutant revalidates through the spec constructor.
    ScenarioSpec.from_dict(a[0].to_dict())
    assert a[0].to_dict() != parent.to_dict()


# -- coverage fingerprints ---------------------------------------------------

def test_run_cell_coverage_and_fingerprint():
    res = run_cell(preset("chaos-kill"))
    cov = res["coverage"]
    assert cov == sorted(set(cov)) and cov
    assert "fault:crash" in cov
    assert any(b.startswith("inv:") for b in cov)
    assert res["fingerprint"] == coverage_fingerprint(cov)
    # Order/duplication-insensitive digest.
    assert coverage_fingerprint(reversed(cov + cov[:2])) == \
        res["fingerprint"]
    digest = cells_digest([res])
    assert digest["coverage_bits"] == len(cov)
    assert digest["fingerprint"] == res["fingerprint"]


# -- the shrinker oracle (designed-bad cell, known 2-event cause) ------------

def test_shrinker_reduces_planted_cell_to_known_minimal_cause():
    """The planted cell carries 5 events; only {corrupt dn2's copies,
    decommission the last clean holder} is the real cause.  ddmin must
    strip the noise spans and land on exactly those 2 events,
    deterministically, and the emitted repro line must rerun RED
    verbatim through the real CLI."""
    spec = planted_violation_spec(SEED)
    planted = run_cell(spec)
    assert not planted["ok"]
    assert [k for k, v in planted["invariants"].items() if not v] == \
        ["zero_silent_loss"]

    sh = shrink_cell(spec)
    assert sh["n_events"] == 2
    assert set(sh["events"]) == {"corrupt:dn2@3:1", "decommission:dn1@5"}
    assert sh["failed"] == ["zero_silent_loss"]
    again = shrink_cell(spec)
    assert again["events"] == sh["events"]

    payload = sh["repro"].split("--spec ", 1)[1].strip().strip("'")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["scenarios", "run", "--spec", payload])
    rerun = json.loads(buf.getvalue())
    assert rc == 1 and not rerun["ok"]


# -- the search loop ---------------------------------------------------------

@pytest.mark.slow
def test_search_finds_new_coverage_and_banks_corpus(tmp_path):
    corpus = str(tmp_path / "corpus")
    got = run_search(seed=SEED, budget_cells=12, corpus_dir=corpus,
                     shrink=False)
    assert got["new_coverage_cells"] >= 1
    assert got["coverage_bits"] > got["baseline_bits"]
    for entry in got["kept"]:
        assert entry["name"] == search_cell_name(SEED,
                                                 entry["fingerprint"])
        assert entry["new_bits"]
    banked = load_corpus(corpus)
    assert [e["name"] for e in banked] == \
        sorted(e["name"] for e in got["kept"])
    # Deterministic: the unbanked A/B mode replays the same trajectory.
    again = run_search(seed=SEED, budget_cells=12, corpus_dir="",
                       bank=False, shrink=False)
    assert [e["name"] for e in again["kept"]] == \
        [e["name"] for e in got["kept"]]
    assert again["fingerprint"] == got["fingerprint"]


def test_distill_is_deterministic_greedy_cover():
    entries = [
        {"name": "c", "spec": {"name": "c"}, "coverage": ["a", "b"],
         "seconds": 2.0},
        {"name": "a", "spec": {"name": "a"}, "coverage": ["a", "b", "x"],
         "seconds": 1.0},
        {"name": "b", "spec": {"name": "b"}, "coverage": ["y"],
         "seconds": 0.5},
        {"name": "d", "spec": {"name": "d"}, "coverage": ["y"],
         "seconds": 0.5},
    ]
    d = distill_corpus(entries)
    assert d["names"] == ["a", "b"]  # greedy gain, then seconds, then name
    assert d["coverage_bits"] == 4
    assert d == distill_corpus(list(reversed(entries)))
    assert d["fingerprint"] == coverage_fingerprint(["a", "b", "x", "y"])


# -- namespaces (search cells can never alias presets) -----------------------

def test_generated_cell_name_prefixes_are_reserved():
    assert not any(n.startswith(RESERVED_NAME_PREFIXES) for n in PRESETS)
    name = search_cell_name(SEED, "deadbeefcafe")
    assert name == f"search-s{SEED}-deadbeef"
    assert name.startswith("search-")


# -- CLI ---------------------------------------------------------------------

def _cli(argv):
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = cli_main(argv)
    return rc, out.getvalue(), err.getvalue()


def test_cli_run_spec_accepts_file_and_banked_entry(tmp_path):
    path = tmp_path / "cell.json"
    path.write_text(json.dumps(preset("chaos-kill").to_dict()))
    rc, out, _ = _cli(["scenarios", "run", "--spec", str(path)])
    assert rc == 0
    assert json.loads(out)["cell"] == "chaos-kill"
    # A banked corpus entry (spec wrapped under "spec") runs as-is.
    wrapped = tmp_path / "entry.json"
    wrapped.write_text(json.dumps(
        {"name": "w", "coverage": [], "spec":
         preset("chaos-kill").to_dict()}))
    rc, out, _ = _cli(["scenarios", "run", "--spec", str(wrapped)])
    assert rc == 0 and json.loads(out)["ok"]


def test_cli_run_spec_file_errors_name_the_path(tmp_path):
    missing = str(tmp_path / "nope.json")
    rc, _, err = _cli(["scenarios", "run", "--spec", missing])
    assert rc == 2
    assert "cannot read spec file" in err and "nope.json" in err
    bad = tmp_path / "bad.json"
    bad.write_text('{"name": "x", "wat": 1}')
    rc, _, err = _cli(["scenarios", "run", "--spec", str(bad)])
    assert rc == 2
    assert "invalid scenario spec" in err and "bad.json" in err


@pytest.mark.slow
def test_cli_search_smoke_writes_corpus_and_distills(tmp_path):
    corpus = str(tmp_path / "corpus")
    rc, out, err = _cli(["scenarios", "search", "--seed", str(SEED),
                         "--budget-cells", "12", "--corpus", corpus,
                         "--distill"])
    assert rc == 0
    digest = json.loads(out)
    assert digest["new_coverage_cells"] >= 1
    assert digest["coverage_bits"] > digest["baseline_bits"]
    dist = json.load(open(os.path.join(corpus, "distilled.json")))
    assert dist["names"] and dist["coverage_bits"] > 0
    # Every distilled cell must rerun green straight from the bank.
    first = os.path.join(corpus, f"{dist['names'][0]}.json")
    if os.path.exists(first):
        rc, out, _ = _cli(["scenarios", "run", "--spec", first])
        assert rc == 0
