"""Bench trajectory regression gate (benchmarks/regress.py,
``cdrs metrics regress``)."""

import json

from cdrs_tpu.benchmarks.regress import (
    append_history,
    check_run,
    extract_records,
    history_key,
    ingest_files,
    load_history,
    main as regress_main,
    write_history,
)


def _hist(values, metric="lloyd_iters_per_sec_n1_d1_k1", unit="iter/s",
          platform="tpu"):
    return [{"round": i + 1, "metric": metric, "value": v, "unit": unit,
             "direction": "higher" if unit == "iter/s" else "lower",
             "platform": platform}
            for i, v in enumerate(values)]


def _run(value, metric="lloyd_iters_per_sec_n1_d1_k1", unit="iter/s",
         platform="tpu"):
    return [{"metric": metric, "value": value, "unit": unit,
             "direction": "higher" if unit == "iter/s" else "lower",
             "platform": platform}]


# -- banding -----------------------------------------------------------------

def test_improvement_passes():
    hist = _hist([100.0, 110.0, 120.0])
    [v] = check_run(_run(200.0), hist)
    assert v["status"] == "improved"
    assert v["baseline"] == 120.0


def test_within_band_noise_passes():
    hist = _hist([100.0, 110.0, 120.0])
    for value in (115.0, 108.0, 120.0, 103.0):  # >= 120 * 0.85
        [v] = check_run(_run(value), hist)
        assert v["status"] == "pass", value


def test_injected_slowdown_fails():
    hist = _hist([100.0, 110.0, 120.0])
    [v] = check_run(_run(120.0 * 0.8), hist)   # 20% below the recent best
    assert v["status"] == "regression"
    assert v["band_low"] == 120.0 * 0.85


def test_steep_trajectory_catches_slowdown_from_latest():
    # The recorded config-2 history shape: a big jump late in the series.
    # A mean/median anchor would let a 20% drop from the latest round
    # through; the recent-best anchor must not.
    hist = _hist([103.0, 288.0, 1773.0, 1888.0])
    [v] = check_run(_run(1888.0 * 0.8), hist)
    assert v["status"] == "regression"


def test_lower_better_direction():
    hist = _hist([3.2, 3.0, 2.9], metric="e2e_seconds_to_categories_n1",
                 unit="s")
    [v] = check_run(_run(2.9 * 1.2, metric="e2e_seconds_to_categories_n1",
                         unit="s"), hist)
    assert v["status"] == "regression"
    [v] = check_run(_run(3.0, metric="e2e_seconds_to_categories_n1",
                         unit="s"), hist)
    assert v["status"] == "pass"
    [v] = check_run(_run(2.0, metric="e2e_seconds_to_categories_n1",
                         unit="s"), hist)
    assert v["status"] == "improved"


def test_platform_mismatch_is_no_baseline():
    hist = _hist([100.0, 110.0])
    [v] = check_run(_run(1.0, platform="cpu"), hist)
    assert v["status"] == "no_baseline"


def test_window_limits_history():
    # Only the trailing 3 rounds form the band: the ancient 10000 must not.
    hist = _hist([10000.0, 100.0, 110.0, 120.0])
    [v] = check_run(_run(118.0), hist, window=3)
    assert v["status"] == "pass"


# -- ingestion ---------------------------------------------------------------

def test_extract_driver_capture_with_nested_blocks():
    doc = {"n": 4, "cmd": "python bench.py", "rc": 0, "tail": "...",
           "parsed": {"metric": "m1", "value": 10.0, "unit": "iter/s",
                      "vs_baseline": 2.0, "backend": "jax",
                      "jax_platform": "tpu", "jax_devices": 1,
                      "config3": {"metric": "m3", "value": 5.0,
                                  "unit": "iter/s", "jax_platform": "tpu"},
                      "config4_rehearsal": {"skipped": "no TPU"}}}
    recs = extract_records(doc, "BENCH_r04.json")
    assert [(r["metric"], r["value"], r["round"]) for r in recs] == \
        [("m1", 10.0, 4), ("m3", 5.0, 4)]
    assert all(r["platform"] == "tpu" for r in recs)


def test_extract_scrapes_truncated_tail():
    # parsed: null + a tail holding only the end of the detail JSON — the
    # BENCH_r05.json shape.  The repeated headline (stdout contract line +
    # detail line) must collapse to one record.
    tail = ('... truncated ..., "metric": "m_head", "value": 42.5, '
            '"unit": "iter/s", "vs_baseline": 7.0, "backend": "jax", '
            '"jax_devices": 1, "jax_platform": "tpu", "config3": '
            '{"metric": "m_c3", "value": 3.25, "unit": "s", '
            '"jax_platform": "tpu"}, "metric": "m_head", "value": 42.5, '
            '"unit": "iter/s"')
    doc = {"n": 5, "cmd": "c", "rc": 0, "parsed": None, "tail": tail}
    recs = extract_records(doc, "BENCH_r05.json")
    by_metric = {r["metric"]: r for r in recs}
    assert set(by_metric) == {"m_head", "m_c3"}
    assert by_metric["m_head"]["value"] == 42.5
    assert by_metric["m_head"]["platform"] == "tpu"
    assert by_metric["m_c3"]["direction"] == "lower"
    assert all(r["round"] == 5 for r in recs)


def test_ingest_real_bench_files_builds_history(tmp_path):
    """The checked-in data/bench_history.jsonl contains the ingest of the
    five BENCH_r0*.json driver captures (later PRs append further records
    — e.g. data/serve_bench.json's serving metrics — so the canonical
    file is a superset, never a rewrite, of the driver ingest)."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(repo, f"BENCH_r0{i}.json") for i in range(1, 6)]
    if not all(os.path.exists(p) for p in paths):  # pragma: no cover
        import pytest

        pytest.skip("BENCH_r0*.json not present")
    records = ingest_files(paths)
    assert len(records) >= 5
    rounds = {r["round"] for r in records}
    assert rounds == {1, 2, 3, 4, 5}
    # config-2 headline metric is present for every round
    headline = [r for r in records
                if r["metric"] == "lloyd_iters_per_sec_n1048576_d32_k128"]
    assert len(headline) == 5
    out = str(tmp_path / "h.jsonl")
    write_history(out, records)
    assert load_history(out) == records
    canonical = os.path.join(repo, "data", "bench_history.jsonl")
    if os.path.exists(canonical):
        have = load_history(canonical)
        for rec in records:
            assert rec in have
        # The appended serving rows are likewise exactly what ingesting
        # their artifact produces (serve_bench.json stamps its own round
        # — the filename carries no rNN), so the whole canonical file is
        # reproducible from `--ingest BENCH_r0*.json data/serve_bench
        # .json` and nothing in it is hand-written.
        serve_json = os.path.join(repo, "data", "serve_bench.json")
        if os.path.exists(serve_json):
            from cdrs_tpu.benchmarks.regress import extract_records

            with open(serve_json, encoding="utf-8") as f:
                serve_recs = extract_records(json.load(f),
                                             "serve_bench.json")
            assert serve_recs
            serve_rows = [h for h in have
                          if str(h.get("metric", "")).startswith("serve_")]
            assert serve_rows == serve_recs
        # And the storage frontier rows (data/storage_bench.json, round
        # 7) under the same append-in-artifact-order contract.
        storage_json = os.path.join(repo, "data", "storage_bench.json")
        if os.path.exists(storage_json):
            from cdrs_tpu.benchmarks.regress import extract_records

            with open(storage_json, encoding="utf-8") as f:
                storage_recs = extract_records(json.load(f),
                                               "storage_bench.json")
            assert storage_recs
            storage_rows = [h for h in have if str(
                h.get("metric", "")).startswith("storage_")]
            assert storage_rows == storage_recs


# -- append/dedup (the automated-bench-history satellite) --------------------

def test_append_history_dedups_and_keeps_order(tmp_path):
    """append_history is the append-only ledger writer: existing rows are
    never rewritten or re-sorted, new rows append in the given order,
    and a (round, metric, platform) key that already exists is skipped —
    re-running a bench or sweep never double-appends."""
    path = str(tmp_path / "h.jsonl")
    first = _hist([100.0, 110.0])  # rounds 1, 2
    assert append_history(path, first) == 2
    assert load_history(path) == first
    newer = _hist([100.0, 110.0, 120.0])  # rounds 1-3: 1, 2 dup
    assert append_history(path, newer) == 1
    rows = load_history(path)
    assert rows == first + [newer[2]]
    # Idempotent: nothing new, file untouched.
    assert append_history(path, newer) == 0
    assert load_history(path) == rows
    # A re-measured value for an existing key keeps the ORIGINAL row.
    remeasured = dict(newer[2], value=999.0)
    assert append_history(path, [remeasured]) == 0
    assert load_history(path) == rows
    assert history_key(remeasured) == history_key(newer[2])


def test_ingest_cli_is_idempotent(tmp_path):
    """`regress --ingest` over an EXISTING history appends-with-dedup
    instead of rewriting: re-running the same ingest is a no-op and the
    original row order survives (the append-only artifact-order
    contract the canonical-history test pins)."""
    hist = str(tmp_path / "h.jsonl")
    b1 = tmp_path / "b1.json"
    b1.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                              "parsed": {"metric": "m", "value": 100.0,
                                         "unit": "iter/s",
                                         "jax_platform": "tpu"}}))
    assert regress_main(["--ingest", str(b1), "--history", hist]) == 0
    rows = load_history(hist)
    assert len(rows) == 1
    # Same artifact again: no change at all.
    assert regress_main(["--ingest", str(b1), "--history", hist]) == 0
    assert load_history(hist) == rows
    # A later round appends AFTER the existing rows (no re-sort, even
    # though ingest_files sorts its own batch).
    b2 = tmp_path / "b2.json"
    b2.write_text(json.dumps({"n": 2, "cmd": "c", "rc": 0, "tail": "",
                              "parsed": {"metric": "a_first", "value": 1.0,
                                         "unit": "iter/s",
                                         "jax_platform": "tpu"}}))
    assert regress_main(["--ingest", str(b2), "--history", hist]) == 0
    assert load_history(hist)[0] == rows[0]


def test_ingest_fresh_build_dedups_within_batch(tmp_path):
    """The fresh-build path runs through the same append/dedup writer:
    ingesting the same artifact twice in ONE command writes one row."""
    hist = str(tmp_path / "h.jsonl")
    b1 = tmp_path / "b1.json"
    b1.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                              "parsed": {"metric": "m", "value": 100.0,
                                         "unit": "iter/s",
                                         "jax_platform": "tpu"}}))
    assert regress_main(["--ingest", str(b1), str(b1),
                         "--history", hist]) == 0
    assert len(load_history(hist)) == 1


def test_explicit_direction_wins():
    """A record carrying its own direction (the scenario sweep's
    lower-is-better byte counts) overrides the unit heuristic."""
    doc = {"bench_records": [
        {"metric": "scenario_x_churn_bytes", "value": 100.0,
         "unit": "bytes", "direction": "lower", "backend": "numpy"}]}
    [rec] = extract_records(doc, "sweep.json")
    assert rec["direction"] == "lower"
    hist = [dict(rec, round=1)]
    [v] = check_run([rec | {"value": 130.0}], hist)
    assert v["status"] == "regression"  # more churn = worse
    [v] = check_run([rec | {"value": 80.0}], hist)
    assert v["status"] == "improved"


# -- CLI ---------------------------------------------------------------------

def test_regress_cli_exit_codes(tmp_path, capsys):
    hist = str(tmp_path / "h.jsonl")
    write_history(hist, _hist([100.0, 110.0, 120.0]))
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"metric": "lloyd_iters_per_sec_n1_d1_k1",
                                "value": 119.0, "unit": "iter/s",
                                "jax_platform": "tpu"}))
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps({"metric": "lloyd_iters_per_sec_n1_d1_k1",
                                "value": 96.0, "unit": "iter/s",
                                "jax_platform": "tpu"}))
    assert regress_main([str(good), "--history", hist]) == 0
    assert regress_main([str(slow), "--history", hist]) == 1
    assert regress_main([str(slow), "--history", hist,
                         "--report-only"]) == 0
    capsys.readouterr()
    assert regress_main([str(slow), "--history", hist, "--json",
                         "--report-only"]) == 0
    verdicts = json.loads(capsys.readouterr().out)
    assert verdicts[0]["status"] == "regression"
    # missing run / history files are usage errors, not tracebacks
    assert regress_main([str(tmp_path / "nope.json"),
                         "--history", hist]) == 2
    assert regress_main([str(good), "--history",
                         str(tmp_path / "nope.jsonl")]) == 2


def test_regress_cli_via_metrics_subcommand(tmp_path):
    from cdrs_tpu.obs.metrics_cli import main as metrics_main

    hist = str(tmp_path / "h.jsonl")
    b1 = tmp_path / "b1.json"
    b1.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                              "parsed": {"metric": "m", "value": 100.0,
                                         "unit": "iter/s",
                                         "jax_platform": "tpu"}}))
    assert metrics_main(["regress", "--ingest", str(b1),
                         "--history", hist]) == 0
    run = tmp_path / "run.json"
    run.write_text(json.dumps({"metric": "m", "value": 99.0,
                               "unit": "iter/s", "jax_platform": "tpu"}))
    assert metrics_main(["regress", str(run), "--history", hist]) == 0
    run.write_text(json.dumps({"metric": "m", "value": 50.0,
                               "unit": "iter/s", "jax_platform": "tpu"}))
    assert metrics_main(["regress", str(run), "--history", hist]) == 1
