"""Parity tests: JAX segment-reduction features vs the NumPy golden model."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.features.jax_backend import compute_features_jax
from cdrs_tpu.features.numpy_backend import compute_features
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=120, seed=9))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=120.0, seed=9))
    return manifest, events


def test_feature_parity(workload):
    manifest, events = workload
    want = compute_features(manifest, events)
    got = compute_features_jax(manifest, events)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(got.writes, want.writes)
    np.testing.assert_allclose(got.reads, want.reads)


def test_feature_parity_with_foreign_events(workload):
    """Events pointing at paths missing from the manifest are masked from the
    counters but still move observation_end (compute_features.py:48,56-60)."""
    manifest, events = workload
    far_future = float(events.ts.max()) + 1000.0
    ev2 = EventLog(
        path_id=np.concatenate([events.path_id, np.array([-1, -1], dtype=np.int32)]),
        ts=np.concatenate([events.ts, np.array([far_future, far_future - 1])]),
        op=np.concatenate([events.op, np.array([1, 0], dtype=np.int8)]),
        client_id=np.concatenate([events.client_id, np.array([0, 1], dtype=np.int32)]),
        clients=events.clients,
    )
    want = compute_features(manifest, ev2)
    got = compute_features_jax(manifest, ev2)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    # observation_end must have shifted age for every file
    assert got.raw[:, 1].min() >= 1000.0


def test_empty_log(workload):
    manifest, _ = workload
    empty = EventLog(
        path_id=np.zeros(0, dtype=np.int32),
        ts=np.zeros(0),
        op=np.zeros(0, dtype=np.int8),
        client_id=np.zeros(0, dtype=np.int32),
        clients=[],
    )
    got = compute_features_jax(manifest, empty, observation_end=1e9)
    want = compute_features(manifest, empty, observation_end=1e9)
    np.testing.assert_allclose(got.raw, want.raw)
    np.testing.assert_allclose(got.norm, want.norm)
    assert (got.raw[:, 3] == 1.0).all()  # locality 1.0 for never-accessed files


def test_kernel_float32_inputs_match_numpy(workload):
    """Production (x32) shape of the kernel: float32 age + int32 second buckets
    must still reproduce the numpy concurrency/age features (the raw epoch
    floats never enter the kernel — they are reduced on host in float64)."""
    import jax.numpy as jnp

    from cdrs_tpu.features.jax_backend import features_kernel

    manifest, events = workload
    want = compute_features(manifest, events)

    obs_end = float(events.ts.max())
    sec_f = np.floor(events.ts)
    sec = (sec_f - sec_f.min()).astype(np.int32)
    age = (obs_end - manifest.creation_ts).astype(np.float32)

    raw, norm, writes, reads = features_kernel(
        jnp.asarray(events.path_id, dtype=jnp.int32),
        jnp.asarray(sec),
        jnp.asarray(events.op),
        jnp.asarray(events.client_id, dtype=jnp.int32),
        jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
        jnp.asarray(age),  # float32: the accelerator default without x64
        len(manifest),
    )
    got = np.asarray(raw)
    # concurrency (col 4) and counters are exact in f32; age (col 1) is
    # magnitude ~3e7 so f32 keeps ~1e-7 relative accuracy.
    np.testing.assert_allclose(got[:, 4], want.raw[:, 4], rtol=0, atol=0)
    np.testing.assert_allclose(got[:, 0], want.raw[:, 0], rtol=0, atol=0)
    np.testing.assert_allclose(got[:, 1], want.raw[:, 1], rtol=1e-6)
