"""Parity tests: JAX segment-reduction features vs the NumPy golden model."""

import numpy as np
import pytest

pytest.importorskip("jax")

from cdrs_tpu.config import GeneratorConfig, SimulatorConfig
from cdrs_tpu.features.jax_backend import compute_features_jax
from cdrs_tpu.features.numpy_backend import compute_features
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=120, seed=9))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=120.0, seed=9))
    return manifest, events


def test_feature_parity(workload):
    manifest, events = workload
    want = compute_features(manifest, events)
    got = compute_features_jax(manifest, events)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(got.writes, want.writes)
    np.testing.assert_allclose(got.reads, want.reads)


def test_feature_parity_with_foreign_events(workload):
    """Events pointing at paths missing from the manifest are masked from the
    counters but still move observation_end (compute_features.py:48,56-60)."""
    manifest, events = workload
    far_future = float(events.ts.max()) + 1000.0
    ev2 = EventLog(
        path_id=np.concatenate([events.path_id, np.array([-1, -1], dtype=np.int32)]),
        ts=np.concatenate([events.ts, np.array([far_future, far_future - 1])]),
        op=np.concatenate([events.op, np.array([1, 0], dtype=np.int8)]),
        client_id=np.concatenate([events.client_id, np.array([0, 1], dtype=np.int32)]),
        clients=events.clients,
    )
    want = compute_features(manifest, ev2)
    got = compute_features_jax(manifest, ev2)
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    # observation_end must have shifted age for every file
    assert got.raw[:, 1].min() >= 1000.0


def test_empty_log(workload):
    manifest, _ = workload
    empty = EventLog(
        path_id=np.zeros(0, dtype=np.int32),
        ts=np.zeros(0),
        op=np.zeros(0, dtype=np.int8),
        client_id=np.zeros(0, dtype=np.int32),
        clients=[],
    )
    got = compute_features_jax(manifest, empty, observation_end=1e9)
    want = compute_features(manifest, empty, observation_end=1e9)
    np.testing.assert_allclose(got.raw, want.raw)
    np.testing.assert_allclose(got.norm, want.norm)
    assert (got.raw[:, 3] == 1.0).all()  # locality 1.0 for never-accessed files


@pytest.mark.parametrize("ndata", [2, 8])
def test_sharded_feature_parity(workload, ndata):
    """Event-sharded kernel over the data mesh is bit-equal to the golden model
    (shards are time-contiguous; edge-second correction makes concurrency exact)."""
    manifest, events = workload
    assert np.all(np.diff(events.ts) >= 0)  # simulator emits a sorted log
    want = compute_features(manifest, events)
    got = compute_features_jax(manifest, events, mesh_shape={"data": ndata})
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(got.writes, want.writes)
    np.testing.assert_allclose(got.reads, want.reads)


def test_sharded_hot_second_spans_shards():
    """A single (path, second) bucket bigger than a whole shard must still
    count exactly once with its full count (the shard-edge psum correction)."""
    from cdrs_tpu.io.events import Manifest

    n = 4
    manifest = Manifest(
        paths=[f"/f{i}" for i in range(n)],
        creation_ts=np.full(n, 1.0e9),
        primary_node_id=np.zeros(n, dtype=np.int32),
        size_bytes=np.ones(n, dtype=np.int64),
        category=["moderate"] * n,
        nodes=["dn1"],
    )
    base = 1.7e9
    # 40 events: 3 in second 0 (file 1), 33 in second 1 (file 0 — spans >4 of
    # the 8 shards of 5 events each), 4 in second 2 (file 2).
    ts = np.concatenate([
        base + np.linspace(0.0, 0.9, 3),
        base + 1.0 + np.linspace(0.0, 0.99, 33),
        base + 2.0 + np.linspace(0.0, 0.9, 4),
    ])
    pid = np.concatenate([
        np.full(3, 1), np.full(33, 0), np.full(4, 2)]).astype(np.int32)
    events = EventLog(ts=ts, path_id=pid, op=np.zeros(40, np.int8),
                      client_id=np.zeros(40, np.int32), clients=["dn1"])
    want = compute_features(manifest, events)
    got = compute_features_jax(manifest, events, mesh_shape={"data": 8})
    assert want.raw[0, 4] == 33.0
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)


def test_sharded_rejects_unsorted_log(workload):
    manifest, events = workload
    shuffled = EventLog(
        ts=events.ts[::-1].copy(), path_id=events.path_id[::-1].copy(),
        op=events.op[::-1].copy(), client_id=events.client_id[::-1].copy(),
        clients=events.clients,
    )
    with pytest.raises(ValueError, match="time-sorted"):
        compute_features_jax(manifest, shuffled, mesh_shape={"data": 4})


def test_sharded_foreign_events_and_padding(workload):
    """Uneven event counts (shard padding) + unknown-path events masked."""
    manifest, events = workload
    k = (len(events) // 8) * 8 + 3  # force padding
    ev = EventLog(ts=events.ts[:k], path_id=events.path_id[:k].copy(),
                  op=events.op[:k], client_id=events.client_id[:k],
                  clients=events.clients)
    ev.path_id[::7] = -1  # scatter foreign paths
    want = compute_features(manifest, ev)
    got = compute_features_jax(manifest, ev, mesh_shape={"data": 8})
    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)


def test_kernel_float32_inputs_match_numpy(workload):
    """Production (x32) shape of the kernel: float32 age + int32 second buckets
    must still reproduce the numpy concurrency/age features (the raw epoch
    floats never enter the kernel — they are reduced on host in float64)."""
    import jax.numpy as jnp

    from cdrs_tpu.features.jax_backend import features_kernel

    manifest, events = workload
    want = compute_features(manifest, events)

    obs_end = float(events.ts.max())
    sec_f = np.floor(events.ts)
    sec = (sec_f - sec_f.min()).astype(np.int32)
    age = (obs_end - manifest.creation_ts).astype(np.float32)

    raw, norm, writes, reads = features_kernel(
        jnp.asarray(events.path_id, dtype=jnp.int32),
        jnp.asarray(sec),
        jnp.asarray(events.op),
        jnp.asarray(events.client_id, dtype=jnp.int32),
        jnp.asarray(manifest.primary_node_id, dtype=jnp.int32),
        jnp.asarray(age),  # float32: the accelerator default without x64
        len(manifest),
    )
    got = np.asarray(raw)
    # concurrency (col 4) and counters are exact in f32; age (col 1) is
    # magnitude ~3e7 so f32 keeps ~1e-7 relative accuracy.
    np.testing.assert_allclose(got[:, 4], want.raw[:, 4], rtol=0, atol=0)
    np.testing.assert_allclose(got[:, 0], want.raw[:, 0], rtol=0, atol=0)
    np.testing.assert_allclose(got[:, 1], want.raw[:, 1], rtol=1e-6)
