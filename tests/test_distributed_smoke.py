"""Two-process jax.distributed rendezvous on localhost CPU (VERDICT r4 #8).

tests/test_distributed.py unit-tests the env detection and mesh math; this
module actually EXECUTES the multi-process path: coordinator + worker
processes (2 virtual CPU devices each) rendezvous over a localhost port,
build the 4-device global mesh, and run the sharded KMeans.  The reference
counterpart is the YARN multi-container path (Makefile:45-60) its compose
cluster exercises.
"""

import json
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_sharded_kmeans(tmp_path):
    port = _free_port()
    outs = [tmp_path / "p0.json", tmp_path / "p1.json"]
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "distributed_worker.py"),
             str(port), str(i), str(outs[i])],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    results = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("distributed workers timed out (rendezvous "
                                 "never completed)")
        results.append((p.returncode, stdout, stderr))
    for rc, stdout, stderr in results:
        if rc != 0 and "Multiprocess computations aren't implemented" \
                in (stdout + stderr):
            # jaxlib builds without CPU cross-process collectives (the
            # rendezvous itself succeeded): an environment limitation of
            # this runner, not a regression in the distributed path.
            pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
        assert rc == 0, f"worker failed:\n{stdout}\n{stderr}"

    a, b = (json.load(open(o)) for o in outs)
    assert a["process_count"] == b["process_count"] == 2
    assert a["global_devices"] == b["global_devices"] == 4
    # Both controllers of one SPMD program: identical results.
    np.testing.assert_array_equal(np.asarray(a["centroids"]),
                                  np.asarray(b["centroids"]))
    assert a["n_iter"] == b["n_iter"]

    # And identical to a single-process run of the same logical mesh (the
    # virtual 8-device conftest mesh, data axis 4): the DCN tier changes
    # where shards live, never what they compute.
    from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(7)
    X_np = rng.normal(size=(4096, 8)).astype(np.float32)
    X_np[:2048] += 4.0
    c_ref, _, it_ref, _ = kmeans_jax_full(
        X_np, 16, seed=3, max_iter=25, mesh_shape={"data": 4})
    assert it_ref == a["n_iter"]
    np.testing.assert_allclose(np.asarray(a["centroids"]),
                               np.asarray(c_ref), rtol=0, atol=0)
