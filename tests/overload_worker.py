"""Worker process for the crash-anywhere daemon fuzz.

Launched by tests/test_overload.py as ``python tests/overload_worker.py
--manifest M --log L.cdrsb --checkpoint C.npz --metrics OUT.jsonl
--kill N:STAGE [--brownout]``.  It builds the EXACT daemon the parent
builds (``make_daemon`` is imported by the test so the two can never
drift), then SIGKILLs its own process at a seeded injection point:

* ``pre``  — immediately before the N-th ``process_window`` call
  (death mid-ingest, the window's events buffered but undecided)
* ``post`` — immediately after the N-th ``process_window`` returns,
  before ANY daemon bookkeeping (cursor advance, record append, epoch
  publish, checkpoint) — the harshest spot: a whole decision computed
  and then lost
* ``save`` — immediately after the first checkpoint write at/after the
  N-th decision lands (death with a fresh durable cursor)

No cleanup handler runs (it is a real ``SIGKILL``); the crash-anywhere
contract (daemon/core.py) says the resumed daemon must replay from the
last durable cursor and produce the same decision stream the
uninterrupted run did.
"""

import argparse
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def make_daemon(manifest_path, *, brownout=False, max_windows=None,
                checkpoint_every=1):
    """The one daemon-under-test constructor the worker AND the parent
    test share: windowed controller with serve + scrub + a benign fault
    schedule (all three brownout levers live), optional aggressive
    brownout thresholds so lag crosses every rung on a pre-written log.
    """
    from cdrs_tpu.config import KMeansConfig, validated_scoring_config
    from cdrs_tpu.control import ControllerConfig, ReplicationController
    from cdrs_tpu.daemon import BrownoutConfig, DaemonConfig, StreamDaemon
    from cdrs_tpu.faults import FaultSchedule, ScrubConfig
    from cdrs_tpu.io.events import Manifest
    from cdrs_tpu.serve import ServeConfig

    manifest = Manifest.read_csv(manifest_path)
    cfg = ControllerConfig(
        window_seconds=120.0, backend="numpy",
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(),
        serve=ServeConfig(policy="p2c", seed=3),
        fault_schedule=FaultSchedule.from_specs(["crash:dn2@3-5"]),
        scrub=ScrubConfig(bytes_per_window=10**9))
    bc = None
    if brownout:
        bc = BrownoutConfig(engage=(0.5, 1.0, 1.5, 2.0, 3.0),
                            release=(0.2, 0.4, 0.6, 0.8, 1.0), hold=1)
    return StreamDaemon(
        ReplicationController(manifest, cfg),
        DaemonConfig(checkpoint_every=checkpoint_every,
                     max_windows=max_windows, brownout=bc))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--manifest", required=True)
    ap.add_argument("--log", required=True)
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--metrics", required=True)
    ap.add_argument("--kill", default=None, metavar="N:STAGE",
                    help="SIGKILL self around the N-th decision: "
                         "pre | post | save")
    ap.add_argument("--brownout", action="store_true")
    args = ap.parse_args()

    daemon = make_daemon(args.manifest, brownout=args.brownout)
    if args.kill:
        n_s, stage = args.kill.split(":")
        kill_n = int(n_s)
        if stage not in ("pre", "post", "save"):
            raise SystemExit(f"unknown kill stage {stage!r}")
        calls = {"n": -1}
        ctl = daemon.controller
        orig_pw = ctl.process_window

        def pw(w, events):
            calls["n"] += 1
            if stage == "pre" and calls["n"] == kill_n:
                os.kill(os.getpid(), signal.SIGKILL)
            rec = orig_pw(w, events)
            if stage == "post" and calls["n"] == kill_n:
                os.kill(os.getpid(), signal.SIGKILL)
            return rec

        ctl.process_window = pw
        if stage == "save":
            orig_save = daemon._save

            def save(path):
                orig_save(path)
                if calls["n"] >= kill_n:
                    os.kill(os.getpid(), signal.SIGKILL)

            daemon._save = save
    daemon.run(args.log, checkpoint_path=args.checkpoint,
               metrics_path=args.metrics)
    return 0


if __name__ == "__main__":
    sys.exit(main())
